"""The node-aware hierarchical host plane (ISSUE 14, DESIGN.md §5l):
node-map agreement, the two-level schedules (uniform shard-parallel and
unequal-node leader relay), per-leg codec arbitration with cross-leg
error feedback, the pure flat-vs-hier algorithm pick, trace/digest
coverage, heal-time repair (leader re-election) under chaos, and the
committed hier_r01 artifact + sentinel floor."""

import json
import os
import threading

import numpy as np
import pytest

from rocnrdma_tpu import distributed as dist, native
from rocnrdma_tpu.metrics import WIRE
from rocnrdma_tpu.obs import trace as obs_trace
from rocnrdma_tpu.transport import bootstrap, plugin, tuner

needs_native = pytest.mark.skipif(not native.available(),
                                  reason="native library not buildable")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# pick_algorithm: pure, topology-priced, deterministic
# ---------------------------------------------------------------------------


def test_pick_algorithm_mixed_topology_prefers_hier():
    shm = tuner.HostWireModel("shm", params=tuner.PlaneParams.from_dict(
        tuner.COMMITTED_HOST_PLANES["shm"]["params"]),
        table=tuner.COMMITTED_HOST_PLANES["shm"]["table"])
    tcp = tuner.HostWireModel("tcp", params=tuner.PlaneParams.from_dict(
        tuner.COMMITTED_HOST_PLANES["tcp"]["params"]),
        table=tuner.COMMITTED_HOST_PLANES["tcp"]["table"])
    # mixed 2x2 at 1 MiB: the hierarchy crosses the slow plane once per
    # shard in parallel instead of 6 sequential tcp hops
    assert tuner.pick_algorithm(1 << 20, (2, 2), flat=tcp,
                                intra=shm) == "hier"
    # pure: same inputs, same verdict, twice
    assert tuner.pick_algorithm(1 << 20, (2, 2), flat=tcp,
                                intra=shm) == "hier"
    # degenerate topologies keep the incumbent
    assert tuner.pick_algorithm(1 << 20, (4,), flat=tcp,
                                intra=shm) == "ring"
    assert tuner.pick_algorithm(0, (2, 2), flat=tcp, intra=shm) == "ring"
    # unequal nodes price the leader relay (whole buffer over the slow
    # plane, twice through the chain): at big sizes the flat ring wins
    assert tuner.pick_algorithm(16 << 20, (2, 1), flat=tcp,
                                intra=shm) == "ring"


def test_pick_algorithm_verb_arms_price_their_own_schedule():
    """The three verbs' flat wire patterns differ: a flat
    reduce-scatter is HALF a flat allreduce while the hierarchical one
    runs the full allreduce schedule plus a slice — pricing everything
    as an allreduce would deterministically pick the slower path
    (review finding). On the committed mixed 2x2 at 1 MiB the
    allreduce verdict is hier but the reduce_scatter verdict must be
    ring."""
    shm = tuner.host_wire_model("shm")
    tcp = tuner.host_wire_model("tcp")
    assert tuner.pick_algorithm(1 << 20, (2, 2), flat=tcp, intra=shm,
                                verb="allreduce") == "hier"
    assert tuner.pick_algorithm(1 << 20, (2, 2), flat=tcp, intra=shm,
                                verb="reduce_scatter") == "ring"
    # tiny sizes are alpha-dominated: fewer sequential slow-plane hops
    # wins for every verb
    assert tuner.pick_algorithm(4096, (2, 2), flat=tcp, intra=shm,
                                verb="reduce_scatter") == "hier"
    assert tuner.pick_algorithm(1 << 18, (2, 2), flat=tcp, intra=shm,
                                verb="allgather") == "hier"
    with pytest.raises(ValueError, match="unknown verb"):
        tuner.pick_algorithm(1 << 20, (2, 2), flat=tcp, intra=shm,
                             verb="broadcast")


def test_pick_algorithm_is_on_the_purity_surface():
    # the pick must be covered by the analyzer's purity pass (the
    # name-contains-pick rule over tuner.py)
    from tools.analyze import purity
    assert purity._is_pick_surface("pick_algorithm", "pick_algorithm")


# ---------------------------------------------------------------------------
# in-process fleets (threads over a sidecar store)
# ---------------------------------------------------------------------------


def _run_group(n, node_of, fn, plane="shm", group="hier-t", server=None,
               timeout=120):
    own = server is None
    if own:
        server = bootstrap.BootstrapServer(n_ranks=n)
    outs: list = [None] * n
    errs: list = []

    def worker(rank):
        pg = None
        try:
            pg = dist.init_process_group(
                rank=rank, world_size=n, store_handle=server.handle,
                group_name=group, plane=plane, node_of=node_of,
                timeout_s=60.0)
            outs[rank] = fn(pg, rank)
        except Exception as e:  # pragma: no cover - surfaced via assert
            import traceback
            traceback.print_exc()
            errs.append((rank, e))
        finally:
            if pg is not None:
                pg.destroy()

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    if own:
        server.close()
    assert not errs, errs
    return outs


@needs_native
def test_hier_allreduce_uniform_bitwise():
    n = 4
    xs = [np.random.default_rng(r).integers(-1000, 1000, 10001)
          for r in range(n)]
    want = np.sum(xs, axis=0)
    base = WIRE.snapshot()
    outs = _run_group(n, [0, 0, 1, 1],
                      lambda pg, r: pg.all_reduce(xs[r],
                                                  algorithm="hier"))
    d = WIRE.delta(base)
    for r in range(n):
        np.testing.assert_array_equal(outs[r], want)
    # the schedule genuinely ran (counted per completed hier collective)
    assert d["hier_ops"] >= n
    assert WIRE.negotiation()["algorithm"] == "hier"


@needs_native
def test_hier_allreduce_unequal_nodes_leader_relay():
    # nodes of size 2 and 1: the relay path (chain reduce onto each
    # node's leader, leaders' ring, chain broadcast) — the shape every
    # post-heal shrunk node runs
    n = 3
    xs = [np.random.default_rng(10 + r).integers(-1000, 1000, 7777)
          for r in range(n)]
    want = np.sum(xs, axis=0)
    outs = _run_group(n, [0, 0, 1],
                      lambda pg, r: pg.all_reduce(xs[r],
                                                  algorithm="hier"),
                      group="hier-u")
    for r in range(n):
        np.testing.assert_array_equal(outs[r], want)


@needs_native
def test_hier_reduce_scatter_matches_flat_slices():
    n = 4
    xs = [np.random.default_rng(20 + r).integers(-1000, 1000, 10001)
          for r in range(n)]
    want = np.sum(xs, axis=0)
    outs = _run_group(n, [0, 0, 1, 1],
                      lambda pg, r: pg.reduce_scatter(xs[r],
                                                      algorithm="hier"),
                      group="hier-rs")
    bounds = [10001 * i // n for i in range(n + 1)]
    for r in range(n):
        np.testing.assert_array_equal(outs[r], want[bounds[r]:bounds[r + 1]])


@needs_native
def test_hier_allgather_interleaved_map_reorders_to_rank_order():
    # node map [0, 1, 0, 1]: node blocks concatenate in NODE order,
    # which is NOT rank order — the reorder must restore it
    n = 4
    xs = [np.random.default_rng(30 + r).standard_normal(513)
          .astype(np.float32) for r in range(n)]
    want = np.stack(xs)
    outs = _run_group(n, [0, 1, 0, 1],
                      lambda pg, r: pg.all_gather(xs[r],
                                                  algorithm="hier"),
                      group="hier-ag")
    for r in range(n):
        np.testing.assert_array_equal(outs[r], want)


@needs_native
def test_same_epoch_rebuild_probes_past_burned_generation():
    # an aborted hier collective at an UNCHANGED epoch (self_heal off)
    # burns its rendezvous generation and invalidates; the retry must
    # rebuild under a FRESH namespace — reusing the consumed one would
    # fetch the dead build's closed listener handles and redial them
    # until deadline
    n = 4
    gate = threading.Barrier(n)
    x0 = np.arange(4096, dtype=np.float32)

    def roundtrip(pg, r):
        r1 = pg.all_reduce(x0 + r, algorithm="hier")
        g1 = pg._hier.gen
        gate.wait(timeout=60)
        # the abort handlers' exact sequence, sans the raise
        pg._hier_burn(pg._hier)
        pg._hier_invalidate()
        gate.wait(timeout=60)
        r2 = pg.all_reduce(x0 + r, algorithm="hier")
        return g1, pg._hier.gen, r1, r2

    outs = _run_group(n, [0, 0, 1, 1], roundtrip, group="hier-gen")
    for g1, g2, r1, r2 in outs:
        assert (g1, g2) == (0, 1)
        np.testing.assert_array_equal(r1, r2)


@needs_native
def test_hierarchy_accessor_and_leaders():
    def info(pg, r):
        return pg.hierarchy(timeout_s=60.0)
    outs = _run_group(4, [0, 0, 1, 1], info, group="hier-i")
    for h in outs:
        assert h["leaders"] == [0, 2]
        assert h["uniform"] is True
        assert h["nodes"] == {"0": [0, 1], "1": [2, 3]}
        assert h["intra_plane"] == "shm"
    # every rank cross-wires on the uniform fast path
    assert all(h["cross_wired"] for h in outs)


@needs_native
def test_node_map_disagreement_refuses_named():
    n = 2
    server = bootstrap.BootstrapServer(n_ranks=n)
    errs: list = [None] * n

    def worker(rank, node_of):
        try:
            pg = dist.init_process_group(
                rank=rank, world_size=n, store_handle=server.handle,
                group_name="hier-bad", plane="shm", node_of=node_of,
                timeout_s=30.0)
            pg.destroy()
        except ValueError as e:
            errs[rank] = str(e)

    threads = [threading.Thread(target=worker, args=(0, [0, 1])),
               threading.Thread(target=worker, args=(1, [0, 0]))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    server.close()
    named = [e for e in errs if e is not None]
    assert len(named) == 1, errs
    assert "node map disagreement" in named[0]


def test_node_map_length_validated():
    with pytest.raises(ValueError, match="node_of must map every rank"):
        dist.ProcessGroup(0, 1, None, None, group_name="hier-len",
                          plane="shm", node_of=[0, 0])


def test_algorithm_knob_validated():
    pg = dist.ProcessGroup(0, 1, None, None, group_name="hier-k",
                           plane="shm")
    try:
        with pytest.raises(ValueError, match="unknown algorithm"):
            pg._pick_wire_algorithm(np.zeros(4, np.float32), "msg", "tree")
        with pytest.raises(ValueError, match="rides the msg wire"):
            pg._pick_wire_algorithm(np.zeros(4, np.float32), "rdma",
                                    "hier")
    finally:
        pg.destroy()


# ---------------------------------------------------------------------------
# per-leg codec arbitration + cross-leg error feedback (mixed planes)
# ---------------------------------------------------------------------------


@needs_native
def test_per_leg_codec_compresses_only_the_cross_leg():
    # group plane tcp (slow inter-node), intra shm: a codec="auto" lane
    # must quantize the CROSS leg only — the committed models say int8
    # on tcp, None on shm — and the re-encode error of the RS-phase
    # partial sum feeds the ResidualStore (the digest moves)
    n = 4
    elems = 1 << 16
    xs = [np.random.default_rng(40 + r).standard_normal(elems)
          .astype(np.float32) for r in range(n)]
    want = np.sum(xs, axis=0)
    base = WIRE.snapshot()

    def run(pg, r):
        ch = pg.channel("q", codec="auto")
        out = ch.all_reduce(xs[r], timeout_s=60.0, algorithm="hier")
        out = ch.all_reduce(xs[r], timeout_s=60.0, algorithm="hier")
        return out, pg.wire_stats()["codec_residual_digest"]

    outs = _run_group(n, [0, 0, 1, 1], run, plane="tcp",
                      group="hier-q")
    d = WIRE.delta(base)
    assert d["frames_encoded"] > 0
    assert d["payload_bytes_saved"] > 0
    assert d["payload_bytes_copied"] == 0
    # cross-leg-only: each rank ships its ~1/ln shard across nodes
    # twice (2 rounds) — if every leg had compressed, savings would be
    # ~3x larger (2 shm legs move the full buffer per round)
    cross_decoded = n * 2 * (elems // 2) * 4
    assert d["payload_bytes_saved"] <= cross_decoded
    tol = 0.05 * float(np.abs(want).max())
    for out, digest in outs:
        assert float(np.abs(out - want).max()) <= tol
        # error feedback is live: the residual store holds state
        from rocnrdma_tpu.transport.codec import ResidualStore
        assert digest != ResidualStore().digest()


@needs_native
def test_explicit_codec_lane_binds_to_the_cross_leg_only():
    # an EXPLICIT int8 lane on the hierarchical path must quantize the
    # cross leg alone, like "auto"'s arbitrated verdict: an intra leg
    # honoring it would quantize the node-local RS partial sums with
    # no error feedback anywhere (the HIER_XLEG residual covers only
    # the cross shard)
    n = 4
    elems = 1 << 14
    xs = [np.random.default_rng(50 + r).standard_normal(elems)
          .astype(np.float32) for r in range(n)]
    want = np.sum(xs, axis=0)
    base = WIRE.snapshot()

    def run(pg, r):
        ch = pg.channel("qx", codec="int8")
        return ch.all_reduce(xs[r], timeout_s=60.0, algorithm="hier")

    outs = _run_group(n, [0, 0, 1, 1], run, group="hier-qx")
    d = WIRE.delta(base)
    assert d["frames_encoded"] > 0
    # savings bounded by the cross-leg decoded bytes alone (each rank
    # ships its ~1/ln shard across nodes once): the shm legs moved the
    # FULL buffer per rank, so any intra-leg encoding would blow this
    cross_decoded = n * (elems // 2) * 4
    assert 0 < d["payload_bytes_saved"] <= cross_decoded
    tol = 0.05 * float(np.abs(want).max())
    for out in outs:
        assert float(np.abs(out - want).max()) <= tol


def test_codec_feedback_hier_xleg_key_is_distinct():
    from rocnrdma_tpu.transport import codec as C
    assert C.HIER_XLEG_VERB == "hier-xleg"
    # the key verb differs from the flat verbs, so a group mixing flat
    # and hierarchical rounds carries independent residuals
    assert C.HIER_XLEG_VERB not in ("all_reduce", "reduce_scatter")


# ---------------------------------------------------------------------------
# the chain legs (plugin) and trace coverage
# ---------------------------------------------------------------------------


@needs_native
def test_chain_reduce_and_bcast_ride_the_stream():
    from rocnrdma_tpu.transport.plugin import (
        HostQPNet,
        ring_chain_bcast_over_net,
        ring_chain_reduce_over_net,
    )
    n = 3
    net = HostQPNet()
    net.init()
    handles, listens = [], []
    for _ in range(n):
        h, l = net.listen()
        handles.append(h)
        listens.append(l)
    xs = [np.random.default_rng(50 + r).integers(-100, 100, 70001)
          for r in range(n)]
    results: list = [None] * n
    errs: list = []

    def worker(rank):
        try:
            s = net.connect(0, handles[(rank + 1) % n])
            r = net.accept(listens[rank])
            red = ring_chain_reduce_over_net(net, s, r, xs[rank], rank, n)
            got = ring_chain_bcast_over_net(
                net, s, r, red if rank == 0 else np.empty_like(xs[0]),
                rank, n)
            results[rank] = (red, got)
        except Exception as e:  # pragma: no cover
            import traceback
            traceback.print_exc()
            errs.append((rank, e))

    base = WIRE.snapshot()
    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    net.close()
    assert not errs, errs
    want = np.sum(xs, axis=0)
    np.testing.assert_array_equal(results[0][0], want)
    assert results[1][0].size == 0 and results[2][0].size == 0
    for r in range(n):
        np.testing.assert_array_equal(results[r][1], want)
    # the relay legs stream (zero staging copies)
    d = WIRE.delta(base)
    assert d["frames_streamed"] > 0
    assert d["payload_bytes_copied"] == 0


def test_trace_hier_records_skip_critical_path_and_digest_covers_legs():
    # two fake single-rank records of one hier op: hop entries span two
    # legs' namespaces; the assembler must keep walls/attribution but
    # extract NO critical path (sub-ring `up` ids are not group ranks)
    def rec(rank, up, legs):
        return {"v": 1, "epoch": 0, "chan": 0, "op": 0,
                "verb": "hier_allreduce", "rank": rank, "up": up,
                "down": up, "members": 1, "hier_legs": legs,
                "t_start": 0.0, "wall_s": 1.0, "n_frames": 2,
                "hops": [[0, 1, 0.0, 0.5, 0.1],
                         [1 << 16, 1, 0.5, 0.9, 0.6]],
                "waits": {b: 0.0 for b in obs_trace.WAIT_BUCKETS}}

    trees = obs_trace.assemble([rec(0, 1, 3), rec(1, 0, 3)], world=2)
    assert len(trees) == 1
    t = trees[0]
    assert t["critical_path"] == [] and t["cp_rank"] is None
    # walls and the five-bucket attribution survive (buckets sum to wall)
    for info in t["ranks"].values():
        assert abs(sum(info["attribution"].values()) - 1.0) < 1e-9
    # the digest is structural over hier_legs: flat-vs-hier records of
    # the same op must NOT hash equal
    a = obs_trace.digest([rec(0, 1, 3)])
    b = obs_trace.digest([rec(0, 1, 0)])
    assert a != b


def test_trace_leg_context_offsets_hops():
    # inside leg k, frame events' hop ids lift into that leg's
    # namespace — two legs' hop 1 must not collide in the op record
    evs = []
    with obs_trace.leg(1):
        obs_trace.record("frame-posted", hop=1, frame=0)
    with obs_trace.leg(2):
        obs_trace.record("frame-posted", hop=1, frame=0)
    # reconstruct via the flight ring's tail (the events carry the
    # offset hop ids)
    from rocnrdma_tpu.obs import FLIGHT
    hops = [a["hop"] for _, kind, a in FLIGHT.events()
            if kind == "frame-posted"][-2:]
    assert hops[0] != hops[1]
    assert hops[0] == 1 + (1 << 16) and hops[1] == 1 + (2 << 16)


# ---------------------------------------------------------------------------
# chaos: kill a node leader mid-collective; heal re-elects and replays
# ---------------------------------------------------------------------------


@needs_native
def test_kill_and_heal_hier_leader_reelects_replay_equal():
    """The hierarchy x heal acceptance run (ISSUE 14): kill-and-heal
    chaos with the round allreduces on the hierarchical schedule and
    the kill landed on a NODE LEADER (rank 2 of node map [0,0,1,1]).
    Survivors heal to epoch 1 on members [0,1,3] — node 1 shrinks to
    {3}, whose lowest surviving original rank IS the re-elected leader
    — the int64 bitwise oracle holds exactly-once on every committed
    round, frames strand and fence, and two same-seed runs print
    identical FAULTLOG/HEALLOG/TRACELOG/FLEET digests.

    This run is ALSO the kill-a-node-agent chaos gate (ISSUE 15): the
    victim — node 1's leader — is node 1's elected telemetry agent, so
    the surviving leader's FLEETTREE line must show the RE-ELECTED
    agent (rank 3, node 1's lowest surviving original) publishing the
    healed generation's tree with every survivor covered, and each
    survivor's HEALTH walk must carry the degraded → healing → ok
    transitions the FLEET digest pins replay-equal."""
    import json as _json

    from rocnrdma_tpu.runtime.multiprocess import run_workers

    def _line(r, key):
        for line in r.stdout.splitlines():
            if line.startswith(key + " "):
                return line[len(key) + 1:]
        raise AssertionError(f"{key} missing from rank {r.process_id}:\n"
                             f"{r.stdout}")

    n, seed, rounds, victim = 4, 11, 6, 2
    runs = [run_workers(n, "kill-and-heal", timeout_s=150.0, seed=seed,
                        rounds=rounds, kill_ranks=str(victim),
                        kill_ops="35", hier=True) for _ in range(2)]
    for results in runs:
        rc = {r.process_id: r.returncode for r in results}
        assert rc[victim] == 7, results[victim].stdout
        for r in results:
            assert r.returncode != -9, \
                f"rank {r.process_id} HUNG:\n{r.stderr}"
            if r.process_id == victim:
                continue
            assert r.returncode == 0, \
                f"survivor {r.process_id} exited {r.returncode}:\n" \
                f"{r.stdout}\n{r.stderr}"
            assert _line(r, "EPOCH") == "1"
            assert _line(r, "MEMBERS") == "[0, 1, 3]"
            # the degraded-then-healed walk every survivor takes (the
            # FLEET digest below pins it replay-equal across runs)
            health = _json.loads(_line(r, "HEALTH"))
            assert ["ok", "degraded", 0] in health, health
            assert ["healing", "ok", 1] in health, health
        assert sum(int(_line(r, "FENCED")) for r in results
                   if r.process_id != victim) > 0
        # the re-elected node agent (rank 3 took dead rank 2's role)
        # published epoch 1's tree: the surviving leader's root digest
        # covers every survivor
        leader = next(r for r in results if r.process_id == 0)
        tree = _json.loads(_line(leader, "FLEETTREE"))
        assert tree["epoch"] == 1
        assert tree["members"] == [0, 1, 3]
        assert tree["root_covers"] == [0, 1, 3], tree
    for a, b in zip(*runs):
        if a.process_id == victim:
            continue
        assert _line(a, "FAULTLOG") == _line(b, "FAULTLOG"), a.process_id
        assert _line(a, "HEALLOG") == _line(b, "HEALLOG"), a.process_id
        assert _line(a, "TRACELOG") == _line(b, "TRACELOG"), a.process_id
        assert _line(a, "FLEET") == _line(b, "FLEET"), a.process_id


# ---------------------------------------------------------------------------
# the committed artifact + sentinel floor
# ---------------------------------------------------------------------------


def test_committed_hier_record_schema():
    path = os.path.join(REPO, "results", "hier_r01.json")
    with open(path) as fp:
        doc = json.load(fp)
    assert doc["schema"] == "hier_r01"
    assert doc["topology"]["node_map"] == [0, 0, 1, 1]
    floors = doc["floors"]
    assert floors["hier_min_x"] == 1.3
    assert floors["at_bytes"] == 1 << 20
    algos = [r["algo"] for r in doc["records"]]
    assert algos == ["ring", "hier", "hier-codec"]
    hier = doc["records"][1]
    hx = hier["extra"]["hier"]
    # the committed capability: hierarchical beat the flat tcp ring by
    # the acceptance multiple at 1 MiB on the mixed topology, bitwise,
    # with the verdict pinned and the schedule genuinely engaged
    assert hx["speedup_best"] >= floors["hier_min_x"]
    assert hx["bitwise_ok"] is True
    assert hx["verdict"] == "hier"
    assert hx["hier_ops"] > 0
    assert hier["extra"]["wire"]["algorithm"] == "hier"
    assert hier["extra"]["wire"]["payload_bytes_copied"] == 0
    # ...and the per-leg codec arm compressed the cross leg only
    codec = doc["records"][2]["extra"]["hier"]
    assert codec["frames_encoded"] > 0
    assert 0 < codec["bytes_saved"] <= codec["hier_ops"] * (1 << 20)


def test_sentinel_hier_floor_fixed_point():
    from tools import sentinel
    path = os.path.join(sentinel.RESULTS, "hier_r01.json")
    with open(path) as fp:
        rows = json.load(fp)["records"]
    assert sentinel.check_hier_floor(rows) == []
    assert "hier_r01.json" in sentinel.COMMITTED_FILES
    import copy
    bad = copy.deepcopy(rows)
    for r in bad:
        hx = r.get("extra", {}).get("hier")
        if hx:
            hx["speedup_best"] = 1.0
    assert sentinel.check_hier_floor(bad), \
        "a sub-floor hier row must be a finding"
    # a 'hier' row that silently fell back to the flat ring is ALSO a
    # finding (its self-relative speedup proves nothing)
    lazy = copy.deepcopy(rows)
    for r in lazy:
        hx = r.get("extra", {}).get("hier")
        if hx:
            hx["hier_ops"] = 0
    assert any("hier_engaged" in f
               for f in sentinel.check_hier_floor(lazy))
