"""Host-plane transport bench: the CLI end-to-end across real processes."""

import json

import pytest

from rocnrdma_tpu import native
from rocnrdma_tpu.bench import bench_host

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not buildable")


def test_cli_end_to_end(tmp_path, capsys):
    out = tmp_path / "host.jsonl"
    rc = bench_host.main(["--ranks", "2", "--sizes", "64K",
                          "--collectives", "allreduce,allgather",
                          "--repeats", "2", "--iters", "2",
                          "--out", str(out)])
    assert rc == 0
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert {r["collective"] for r in rows} == {"allreduce", "allgather"}
    assert all(r["platform"] == "host-tcp" and r["n_ranks"] == 2
               and r["mean_s"] > 0 for r in rows)
    # the leader's fleet snapshot rides every record: per-rank health,
    # bucket-exact merged histograms, the worst-rank P99 the table shows
    for r in rows:
        fl = r["extra"]["fleet"]
        assert fl["health"] == {"0": "ok", "1": "ok"}, fl
        assert fl["missing"] == [] and fl["epoch"] == 0
        assert fl["worst_p99_us"] > 0
        assert fl["verb_latency"]  # merged histograms attached
    table = capsys.readouterr().out
    assert "allreduce" in table and "ring" in table
    assert "wp99(us)" in table.splitlines()[0]


def test_build_input_shapes():
    import numpy as np
    rng = np.random.default_rng(0)
    assert bench_host._build_input("allreduce", 4, 100, rng).shape == (100,)
    assert bench_host._build_input("allgather", 4, 100, rng).shape == (25,)
    assert bench_host._build_input("alltoall", 4, 100, rng).shape == (4, 25)


def test_alltoallv_on_the_native_wire(tmp_path):
    # the RCCL ncclAllToAllv extension benched on the wire it ships on:
    # ragged trains (skewed deterministic counts), shm plane
    out = tmp_path / "v.jsonl"
    rc = bench_host.main(["--ranks", "3", "--plane", "shm",
                          "--sizes", "64K", "--collectives", "alltoallv",
                          "--repeats", "2", "--iters", "2",
                          "--out", str(out)])
    assert rc == 0
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert rows and all(r["collective"] == "alltoallv" for r in rows)
    # ragged: actual bytes differ from the dense elems*4
    assert all(r["size_bytes"] != 64 * 1024 for r in rows)


def test_ragged_v_legs_on_the_native_wire(tmp_path):
    # the allgatherv / reduce-scatter-v bench legs across real OS
    # processes (VERDICT r2 item 8's bench-surface completion)
    import json
    out = tmp_path / "ragged.jsonl"
    rc = bench_host.main(["--ranks", "2", "--sizes", "64K", "--plane", "shm",
                          "--collectives", "allgatherv,reducescatterv",
                          "--repeats", "2", "--iters", "2",
                          "--out", str(out)])
    assert rc == 0
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert {r["collective"] for r in rows} == {"allgatherv",
                                               "reducescatterv"}
    assert all(r["mean_s"] > 0 and r["busbw_GBps"] > 0 for r in rows)


def test_ragged_counts_deterministic():
    import numpy as np
    c = bench_host._ragged_counts(4, 100)
    assert c.shape == (4,) and (c >= 1).all()
    np.testing.assert_array_equal(c, bench_host._ragged_counts(4, 100))
    assert len(set(c.tolist())) > 1  # genuinely ragged


def test_alltoallv_counts_deterministic_skewed_balanced():
    import numpy as np
    for n in (3, 4, 5, 8):
        c = bench_host._alltoallv_counts(n, 100)
        np.testing.assert_array_equal(c, bench_host._alltoallv_counts(n, 100))
        assert c.shape == (n, n) and c.min() >= 1
        # Latin square: every row spans the full 25-175% range...
        for r in range(n):
            assert len(set(c[r])) == n
        # ...and every rank's TOTAL sent bytes is equal, so size_bytes
        # and the busbw factor mean the same thing on every rank
        assert len(set(c.sum(axis=1))) == 1


def test_smoke_perf_gate(tmp_path, capsys):
    """The tier-1 zero-copy perf gate, now per data path (ROADMAP
    "smoke-gate floors per plane", closed in PR 6): 2 ranks, 1 MiB
    allreduce on shm, tcp, AND the put-based rdma ring must each stage
    ZERO payload bytes through copies on the steady path (every worker
    rank enforces its own counters) and hold >= 0.8x that path's
    recorded GB/s floor. A regression back to the copy-bound wire — on
    any path — fails here before it can ship.

    PR 9 adds the LANES path: the multi-tenant QoS scenario (a 64 KiB
    allreduce on a high-priority lane timed under a saturating bulk
    allgather on a paced lane, concurrently in flight on one comm) —
    gated on both lanes' correctness, the measurement being genuinely
    under load, the latency lane's P99 inside the recorded ceiling,
    and the bulk lane not being starved either.

    PR 11 adds the COALESCE path: many small allreduces unbatched vs
    fused through the async coalescer — gated on the fused stream
    beating the per-op floor by the recorded multiple with the
    bitwise oracle preserved (and the zero-copy contract holding with
    the coalescer ACTIVE, not just importable).

    PR 13 adds the CODEC path: the tcp 1 MiB allreduce over the int8
    quantized wire (per-frame-scale compression, error feedback ON) —
    gated on the int8 arm's best trial beating the committed fp32 tcp
    floor by the recorded multiple (mean held to the standard 0.8x
    allowance of the same bar) with the codec provably engaged and
    zero steady-path copies.

    ISSUE 14 adds the HIER path: the node-aware two-level schedule on
    a simulated 2-node x 2-rank mixed shm/tcp fleet — gated on the
    hierarchical arm beating the same-run flat tcp ring by the
    recorded multiple with the pick_algorithm verdict pinned on the
    negotiation gauge, the bitwise oracle held, the per-leg codec arm
    compressing the cross leg, and zero steady-path copies."""
    out = tmp_path / "smoke.jsonl"
    rc = bench_host.main(["--smoke", "--out", str(out)])
    assert rc == 0
    printed = capsys.readouterr().out
    assert "smoke gate ok [shm]" in printed
    assert "smoke gate ok [tcp]" in printed
    assert "smoke gate ok [rdma]" in printed
    assert "smoke gate ok [lanes]" in printed
    assert "smoke gate ok [coalesce]" in printed
    assert "smoke gate ok [codec]" in printed
    assert "smoke gate ok [hier]" in printed
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert [r["platform"] for r in rows] == ["host-shm", "host-tcp",
                                             "host-shm", "host-shm",
                                             "host-shm", "host-shm",
                                             "host-tcp", "host-tcp",
                                             "host-tcp", "host-tcp",
                                             "host-tcp", "host-tcp"]
    assert [r["algo"] for r in rows] == ["ring", "ring", "ring_rdma",
                                         "lanes", "unbatched", "coalesced",
                                         "ring", "codec-int8", "codec-fp8",
                                         "ring", "hier", "hier-codec"]
    # the hier arm provably ran the two-level schedule with the
    # verdict pinned (ISSUE 14) and the bitwise oracle held
    hier = rows[10]
    assert hier["extra"]["wire"]["algorithm"] == "hier"
    assert hier["extra"]["wire"]["hier_ops"] > 0
    assert hier["extra"]["hier"]["bitwise_ok"] is True
    for row in rows:
        # the coalesce pair shares one measurement window: its wire
        # delta rides the coalesced row only
        if row["algo"] == "unbatched":
            continue
        wire = row["extra"]["wire"]
        assert wire["payload_bytes_copied"] == 0, row["algo"]
        # the one-sided put ring moves whole hops by RDMA write — no
        # streamed frames by design; the message-passing paths must
        # stream
        if row["algo"] == "ring":
            assert wire["frames_streamed"] > 0
        # overlap is timing-dependent (a loaded CI box can legitimately
        # see a peer that never runs ahead), so it is RECORDED, not gated
        # — only the deterministic zero-copy contract above fails the
        # build
        assert 0.0 <= wire["overlap_ratio"] <= 1.0
    # the quantized-wire rows (ISSUE 13): the int8 arm beat the fp32
    # floor bar on its best trial with the codec genuinely engaged
    int8_row = rows[7]
    cx = int8_row["extra"]["codec"]
    assert int8_row["extra"]["wire"]["codec"] == "int8"
    assert int8_row["extra"]["wire"]["frames_encoded"] > 0
    assert int8_row["extra"]["wire"]["payload_bytes_saved"] > 0
    assert cx["floor_x_best"] >= bench_host.SMOKE_CODEC_X
    assert cx["floor_x"] >= 0.8 * bench_host.SMOKE_CODEC_X
    assert cx["max_abs_err"] > 0  # genuinely lossy, genuinely measured
    assert rows[8]["extra"]["wire"]["codec"] == "fp8"
    co_row = rows[5]
    co = co_row["extra"]["coalesce"]
    assert co["bitwise_ok"] and co["speedup"] >= bench_host.SMOKE_COALESCE_SPEEDUP
    assert co_row["extra"]["wire"]["ops_coalesced"] >= co["ops"]
    lanes_row = rows[3]
    ex = lanes_row["extra"]
    assert ex["lane"] == "latency" and ex["lanes_ok"] and ex["overlap_ok"]
    assert 0 < ex["p99_us"] <= bench_host.SMOKE_LANES_P99_US
    assert ex["bulk_GBps"] >= bench_host.SMOKE_LANES_BULK_GBPS
    # both tenants' frames moved on their OWN lanes (the per-channel
    # wire counters attribute them by lane name)
    per_lane = ex["wire"]["channel_bytes_streamed"]
    assert per_lane.get("bulk", 0) > 0 and per_lane.get("latency", 0) > 0
    # the lane column made it to the table, tagging the latency row
    hdr = next(l for l in printed.splitlines() if "wp99(us)" in l)
    assert "lane" in hdr
    assert any("latency" in l for l in printed.splitlines())
