"""Host-plane transport bench: the CLI end-to-end across real processes."""

import json

import pytest

from rocnrdma_tpu import native
from rocnrdma_tpu.bench import bench_host

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not buildable")


def test_cli_end_to_end(tmp_path, capsys):
    out = tmp_path / "host.jsonl"
    rc = bench_host.main(["--ranks", "2", "--sizes", "64K",
                          "--collectives", "allreduce,allgather",
                          "--repeats", "2", "--iters", "2",
                          "--out", str(out)])
    assert rc == 0
    rows = [json.loads(l) for l in out.read_text().splitlines()]
    assert {r["collective"] for r in rows} == {"allreduce", "allgather"}
    assert all(r["platform"] == "host-tcp" and r["n_ranks"] == 2
               and r["mean_s"] > 0 for r in rows)
    table = capsys.readouterr().out
    assert "allreduce" in table and "ring" in table


def test_build_input_shapes():
    import numpy as np
    rng = np.random.default_rng(0)
    assert bench_host._build_input("allreduce", 4, 100, rng).shape == (100,)
    assert bench_host._build_input("allgather", 4, 100, rng).shape == (25,)
    assert bench_host._build_input("alltoall", 4, 100, rng).shape == (4, 25)
