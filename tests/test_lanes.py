"""Multi-tenant collective lanes (ISSUE 9): per-channel wire identity,
priority/credit scheduling, ProcessGroup.channel handles, lane x epoch
and lane x fault composition.

The headline here is the CONCURRENCY PROOF: one ProcessGroup per rank,
a bulk allgather and four small allreduces in flight SIMULTANEOUSLY
over the same comm pair (five threads per rank, released together by a
barrier), every lane's result bitwise-correct — the serialization the
group layer used to impose is gone, and the (chan, tag) wire identity
is what keeps the interleaved frames apart.
"""

import threading
import time

import numpy as np
import pytest

from rocnrdma_tpu import distributed as dist
from rocnrdma_tpu import native
from rocnrdma_tpu.metrics import WIRE
from rocnrdma_tpu.obs import fleet
from rocnrdma_tpu.transport import bootstrap, lanes
from rocnrdma_tpu.transport.faults import FaultNet, FaultSchedule
from rocnrdma_tpu.transport.plugin import HostQPNet

needs_native = pytest.mark.skipif(
    not native.available(), reason="native rqp library not buildable")


# ---------------------------------------------------------------------------
# lane identity: ids, registry, context
# ---------------------------------------------------------------------------


def test_lane_id_stable_and_default_zero():
    assert lanes.lane_id("default") == 0
    a, b = lanes.lane_id("bulk"), lanes.lane_id("bulk")
    assert a == b != 0  # pure function of the name: cross-rank, no store
    assert lanes.lane_id("latency") not in (0, a)


def test_registry_open_idempotent_conflict_refused():
    reg = lanes.LaneRegistry()
    assert len(reg) == 1  # the default lane exists from construction
    lane = reg.open("bulk", priority=1, credit_bytes=1 << 20)
    assert reg.open("bulk", priority=1, credit_bytes=1 << 20) is lane
    with pytest.raises(ValueError, match="conflicting re-open"):
        reg.open("bulk", priority=3, credit_bytes=1 << 20)
    assert reg.get(lane.id) is lane
    assert reg.label(lane.id) == "bulk"
    assert reg.label(0) == "default"
    # an unregistered wire channel still labels (frames can arrive on a
    # lane the local process never opened)
    assert reg.label(0xDEADBEEF).startswith("c")


def test_lane_context_nests_and_restores():
    assert lanes.current_channel() == 0
    with lanes.lane_context(7):
        assert lanes.current_channel() == 7
        with lanes.lane_context(9):
            assert lanes.current_channel() == 9
        assert lanes.current_channel() == 7
    assert lanes.current_channel() == 0


def test_lane_context_is_thread_local():
    seen = []

    def other():
        seen.append(lanes.current_channel())

    with lanes.lane_context(5):
        t = threading.Thread(target=other)
        t.start()
        t.join(timeout=10)
    assert seen == [0]


# ---------------------------------------------------------------------------
# the wire: frames land in their lane's stash, fences count per lane
# ---------------------------------------------------------------------------


@pytest.fixture
def host_pair():
    net = HostQPNet()
    net.init()
    handle, listen_qp = net.listen()
    out = {}
    t = threading.Thread(
        target=lambda: out.setdefault("send", net.connect(0, handle)))
    t.start()
    recv_comm = net.accept(listen_qp)
    t.join(timeout=10)
    yield net, out["send"], recv_comm
    net.close()


@needs_native
def test_frames_match_only_their_own_lane(host_pair):
    net, send_comm, recv_comm = host_pair
    ch = net.open_lane("a", priority=1).id
    net.isend(send_comm, net.reg_mr(send_comm, b"laned!"), tag=4,
              channel=ch)
    # the default lane's receive must NOT see the laned frame
    r0 = net.irecv(recv_comm, 6, tag=4, channel=0)
    deadline = time.monotonic() + 0.5
    while time.monotonic() < deadline:
        assert r0.test() == (False, 0)
    # the laned receive does
    r1 = net.irecv(recv_comm, 6, tag=4, channel=ch)
    assert r1.wait() == b"laned!"


@needs_native
def test_lane_context_is_the_default_channel(host_pair):
    net, send_comm, recv_comm = host_pair
    ch = net.open_lane("ctx", priority=2).id
    with lanes.lane_context(ch):
        net.isend(send_comm, net.reg_mr(send_comm, b"via-ctx!"), tag=9)
    got = net.irecv(recv_comm, 8, tag=9, channel=ch).wait()
    assert got == b"via-ctx!"


@needs_native
def test_epoch_fence_counts_per_lane(host_pair):
    net, send_comm, recv_comm = host_pair
    a = net.open_lane("tenant-a").id
    b = net.open_lane("tenant-b", priority=3).id
    base = WIRE.snapshot()
    for chan, tag in ((a, 1), (a, 2), (b, 1), (0, 5)):
        net.isend(send_comm, net.reg_mr(send_comm, b"x" * 16), tag=tag,
                  channel=chan)
    # deliver into the stash (unconsumed), then fence the generation
    deadline = time.monotonic() + 5.0
    while sum(len(v) for v in recv_comm._unexpected.values()) < 4:
        recv_comm._pump()
        assert time.monotonic() < deadline, recv_comm._unexpected
    net.set_epoch(1)
    d = WIRE.delta(base)
    assert d["frames_fenced"] >= 4
    per = d["channel_frames_fenced"]
    assert per.get("tenant-a", 0) >= 2
    assert per.get("tenant-b", 0) >= 1
    assert per.get("default", 0) >= 1
    assert not recv_comm._unexpected  # every lane's stale frames dropped


# ---------------------------------------------------------------------------
# the gate: credit pacing, strict priority, named starvation timeout
# ---------------------------------------------------------------------------


class _FakeComm:
    def __init__(self):
        self.pumps = 0

    def _pump(self):
        self.pumps += 1


def test_gate_single_lane_is_free_and_credit_paces():
    reg = lanes.LaneRegistry()
    gate = lanes.LaneGate(reg)
    comm = _FakeComm()
    gate.admit(comm, 0, 1 << 30, timeout_s=0.1)  # single lane: no gate
    bulk = reg.open("bulk", credit_bytes=64)
    base = WIRE.snapshot()
    gate.admit(comm, bulk.id, 40, timeout_s=5.0)   # within credit
    gate.admit(comm, bulk.id, 40, timeout_s=5.0)   # over: one yield, then ok
    d = WIRE.delta(base)
    assert d["lane_yields"] >= 1
    assert comm.pumps >= 1  # the yield pumped the comm


def test_gate_defers_behind_higher_priority_intent_then_admits():
    reg = lanes.LaneRegistry()
    gate = lanes.LaneGate(reg)
    comm = _FakeComm()
    bulk = reg.open("bulk", priority=0, credit_bytes=1 << 20)
    lat = reg.open("lat", priority=9)
    # a declared higher-priority intent defers the bulk admit...
    st = gate._state(comm)
    st["intents"][lat.priority] = 1
    done = []

    def admit_bulk():
        gate.admit(comm, bulk.id, 100, timeout_s=10.0)
        done.append(time.monotonic())

    t = threading.Thread(target=admit_bulk)
    t.start()
    time.sleep(0.15)
    assert not done  # still deferred
    with gate._lock:
        st["intents"].pop(lat.priority)
    t.join(timeout=10)
    assert done  # ...and admits the moment the intent clears


def test_gate_starved_lane_raises_named():
    reg = lanes.LaneRegistry()
    gate = lanes.LaneGate(reg)
    comm = _FakeComm()
    bulk = reg.open("bulk2", priority=0)
    lat = reg.open("lat2", priority=9)
    st = gate._state(comm)
    st["intents"][lat.priority] = 1  # never clears
    with pytest.raises(TimeoutError, match="bulk2.*starved"):
        gate.admit(comm, bulk.id, 100, timeout_s=0.2)


# ---------------------------------------------------------------------------
# per-channel fault injection (lane x FaultNet)
# ---------------------------------------------------------------------------


@needs_native
def test_per_channel_partition_blackholes_one_tenant():
    def build():
        sched = FaultSchedule(31, 0, chan_partition_after_ops={"bulk": 2})
        net = FaultNet(HostQPNet(), sched)
        net.init()
        handle, listen_qp = net.listen()
        out = {}
        t = threading.Thread(
            target=lambda: out.setdefault("send", net.connect(0, handle)))
        t.start()
        recv_comm = net.accept(listen_qp)
        t.join(timeout=10)
        return sched, net, out["send"], recv_comm

    sched, net, send_comm, recv_comm = build()
    try:
        bulk = net.open_lane("bulk").id
        # bulk ops 1-2 (send+recv) deliver; bulk ops 3+ blackhole;
        # the default lane flows freely throughout
        net.isend(send_comm, net.reg_mr(send_comm, b"one"), tag=1,
                  channel=bulk)
        assert net.irecv(recv_comm, 3, tag=1, channel=bulk).wait() == b"one"
        net.isend(send_comm, net.reg_mr(send_comm, b"two"), tag=2,
                  channel=bulk)
        r = net.irecv(recv_comm, 3, tag=2, channel=bulk)
        net.isend(send_comm, net.reg_mr(send_comm, b"ok!"), tag=3)
        assert net.irecv(recv_comm, 3, tag=3).wait() == b"ok!"
        deadline = time.monotonic() + 0.5
        while time.monotonic() < deadline:
            assert not r.test()[0]  # the partitioned tenant never completes
        assert sched.counters.counts.get("chan-partitioned", 0) >= 1
    finally:
        net.close()
    # replay: same seed, same call sequence -> identical injection log
    first = sched.fingerprint()
    sched2, net2, send2, recv2 = build()
    try:
        bulk = net2.open_lane("bulk").id
        net2.isend(send2, net2.reg_mr(send2, b"one"), tag=1, channel=bulk)
        assert net2.irecv(recv2, 3, tag=1, channel=bulk).wait() == b"one"
        net2.isend(send2, net2.reg_mr(send2, b"two"), tag=2, channel=bulk)
        r = net2.irecv(recv2, 3, tag=2, channel=bulk)
        net2.isend(send2, net2.reg_mr(send2, b"ok!"), tag=3)
        assert net2.irecv(recv2, 3, tag=3).wait() == b"ok!"
        r.test()
    finally:
        net2.close()
    assert sched2.fingerprint() == first


def test_chan_test_delay_uses_its_own_stream():
    # a laned delay draws from the lane's OWN rng/draw counter: the
    # global stream never advances for it, so default-lane logs are
    # byte-identical with and without laned traffic interleaved
    plain = FaultSchedule(5, 0, test_delay_p=1.0, test_delay_polls=(2, 2))
    mixed = FaultSchedule(5, 0, test_delay_p=1.0, test_delay_polls=(2, 2),
                          chan_test_delay_p={"bulk": 1.0})
    seq = []
    for s in (plain, mixed):
        seq.append([s.test_delay() for _ in range(3)])
    assert seq[0] == seq[1]
    mixed2 = FaultSchedule(5, 0, test_delay_p=1.0, test_delay_polls=(2, 2),
                           chan_test_delay_p={"bulk": 1.0})
    got = [mixed2.test_delay(), mixed2.test_delay(lane="bulk"),
           mixed2.test_delay(), mixed2.test_delay()]
    assert got[0] == seq[0][0] and got[2] == seq[0][1] and got[3] == seq[0][2]
    assert got[1] == 2  # the lane's own draw
    assert any(k == "chan-test-delayed" for _, k, _ in mixed2.log)


# ---------------------------------------------------------------------------
# ProcessGroup.channel: the concurrency proof + default-lane identity
# ---------------------------------------------------------------------------


@pytest.fixture
def sidecar_store():
    servers = []

    def factory(n):
        s = bootstrap.BootstrapServer(n_ranks=n)
        servers.append(s)
        return s

    yield factory
    for s in servers:
        s.close()


def _lane_input(rank: int, lane: str, i: int, elems: int) -> np.ndarray:
    rng = np.random.default_rng((rank, hash(lane) % (1 << 32), i))
    return rng.integers(-1_000_000, 1_000_000, elems).astype(np.int64)


@needs_native
def test_concurrent_bulk_and_four_latency_lanes_bitwise(sidecar_store):
    """THE concurrency proof (ISSUE 9 acceptance): one comm pair per
    rank carries a bulk allgather AND four small allreduces in flight
    simultaneously — five lane threads per rank released by one
    barrier — and every lane's every result is bitwise-correct. The
    bulk block rides the LG put path (>= LG_MIN), the small lanes ride
    the frame ring: both data paths interleave on one wire."""
    n = 2
    store = sidecar_store(n)
    lat_names = [f"lat{i}" for i in range(4)]
    bulk_elems = (4 << 20) // 8   # 4 MiB int64 -> LG path
    small_elems = (16 << 10) // 8
    iters = 4

    def rank_main(rank):
        pg = dist.init_process_group(rank=rank, world_size=n,
                                     store_handle=store.handle,
                                     group_name="lanes-conc", plane="shm")
        try:
            bulk = pg.channel("bulk", priority=0, credit_bytes=1 << 20)
            lats = [pg.channel(nm, priority=5) for nm in lat_names]
            start = threading.Barrier(1 + len(lats))
            errors = []

            def bulk_main():
                try:
                    start.wait(timeout=30)
                    for i in range(iters):
                        mine = _lane_input(rank, "bulk", i, bulk_elems)
                        rows = bulk.all_gather(mine, timeout_s=120.0)
                        for r in range(n):
                            want = _lane_input(r, "bulk", i, bulk_elems)
                            assert np.array_equal(rows[r], want), \
                                ("bulk", i, r)
                except Exception as e:  # noqa: BLE001
                    errors.append(("bulk", repr(e)))

            def lat_main(ch):
                try:
                    start.wait(timeout=30)
                    for i in range(iters):
                        mine = _lane_input(rank, ch.name, i, small_elems)
                        got = ch.all_reduce(mine, timeout_s=60.0)
                        want = _lane_input(0, ch.name, i, small_elems)
                        for r in range(1, n):
                            want = want + _lane_input(r, ch.name, i,
                                                      small_elems)
                        assert np.array_equal(got, want), (ch.name, i)
                except Exception as e:  # noqa: BLE001
                    errors.append((ch.name, repr(e)))

            threads = [threading.Thread(target=bulk_main)]
            threads += [threading.Thread(target=lat_main, args=(ch,))
                        for ch in lats]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180)
            assert not errors, errors
            assert not any(t.is_alive() for t in threads), "lane thread hung"
            return True
        finally:
            pg.destroy()

    base = WIRE.snapshot()
    results = [None] * n
    rank_errors = []

    def runner(r):
        try:
            results[r] = rank_main(r)
        except Exception as e:  # noqa: BLE001
            rank_errors.append((r, repr(e)))

    ts = [threading.Thread(target=runner, args=(r,)) for r in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=240)
    assert not rank_errors, rank_errors
    assert results == [True] * n
    # every lane genuinely moved frames on its OWN channel
    per = WIRE.delta(base)["channel_bytes_streamed"]
    assert per.get("bulk", 0) > 0, per
    for nm in lat_names:
        assert per.get(nm, 0) > 0, per


@needs_native
def test_default_channel_is_lane_zero_and_counts_as_default(sidecar_store):
    n = 2
    store = sidecar_store(n)
    base = WIRE.snapshot()

    def fn(rank):
        pg = dist.init_process_group(rank=rank, world_size=n,
                                     store_handle=store.handle,
                                     group_name="lanes-default",
                                     plane="shm")
        try:
            ch = pg.channel("default")
            assert ch.channel_id == 0 and ch.priority == 0
            x = np.full(1024, rank + 1.0, np.float32)
            got = pg.all_reduce(x)        # plain verb: lane 0
            got2 = ch.all_reduce(x)       # default handle: same lane
            np.testing.assert_allclose(got, np.full(1024, 3.0, np.float32))
            np.testing.assert_allclose(got2, got)
            return True
        finally:
            pg.destroy()

    results = [None] * n
    errs = []

    def runner(r):
        try:
            results[r] = fn(r)
        except Exception as e:  # noqa: BLE001
            errs.append((r, repr(e)))

    ts = [threading.Thread(target=runner, args=(r,)) for r in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=90)
    assert not errs, errs
    assert results == [True] * n
    per = WIRE.delta(base)["channel_frames_streamed"]
    assert per.get("default", 0) > 0, per  # un-laned traffic IS lane 0


def test_channel_conflicting_reopen_refused(sidecar_store):
    store = sidecar_store(1)
    pg = dist.init_process_group(rank=0, world_size=1,
                                 store_handle=store.handle,
                                 group_name="lanes-conflict", plane="shm")
    try:
        ch = pg.channel("bulk", priority=2, credit_bytes=1 << 20)
        assert pg.channel("bulk", priority=2, credit_bytes=1 << 20) is ch
        with pytest.raises(ValueError, match="conflicting re-open"):
            pg.channel("bulk", priority=7)
    finally:
        pg.destroy()


# ---------------------------------------------------------------------------
# fleet: per-channel throughput aggregates cross-rank
# ---------------------------------------------------------------------------


def _snap(orig, epoch, window, chan_bytes):
    return {
        "v": 1, "rank": orig, "orig": orig, "epoch": epoch, "seq": 1,
        "plane": "shm", "health": "ok", "transitions": [], "heals": 0,
        "window_s": window,
        "wire": {"payload_bytes_streamed": sum(chan_bytes.values()),
                 "channel_bytes_streamed": dict(chan_bytes)},
        "wire_delta": {"payload_bytes_streamed": sum(chan_bytes.values()),
                       "channel_bytes_streamed": dict(chan_bytes)},
        "verb_latency": {}, "flight": {"recorded": 0, "capacity": 64},
    }


def test_fleet_aggregates_per_channel_throughput():
    snaps = [_snap(0, 0, 2.0, {"bulk": 4_000_000_000, "latency": 2_000_000}),
             _snap(1, 0, 2.0, {"bulk": 4_000_000_000})]
    out = fleet.aggregate(snaps, epoch=0, members=[0, 1])
    assert out["channel_GBps"]["bulk"] == pytest.approx(4.0)
    assert out["channel_GBps"]["latency"] == pytest.approx(0.001)
    # the per-lane split also survives the exact wire-counter merge
    assert out["wire_totals"]["channel_bytes_streamed"]["bulk"] \
        == 8_000_000_000
    text = fleet.format_fleet(out)
    assert "lanes:" in text and "bulk=" in text


def test_fleet_format_without_lanes_says_so():
    out = fleet.aggregate([_snap(0, 0, 0.0, {})], epoch=0, members=[0])
    assert "no laned traffic" in fleet.format_fleet(out)
