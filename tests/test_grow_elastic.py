"""Elastic grow, warm spares, and the widened retry surfaces (ISSUE 6).

Unit-level coverage of the grow/promote/resume machinery over threads
(one ProcessGroup per thread, a shared sidecar store — the
test_distributed harness shape); the real-process chaos acceptance runs
live in test_chaos_soak.py.
"""

import threading
import time

import numpy as np
import pytest

from rocnrdma_tpu import distributed as dist
from rocnrdma_tpu import native
from rocnrdma_tpu.transport import bootstrap

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library not buildable")


@pytest.fixture
def sidecar_store():
    servers = []

    def factory(n):
        s = bootstrap.BootstrapServer(n_ranks=n)
        servers.append(s)
        return s
    yield factory
    for s in servers:
        s.close()


def _run_threads(workers):
    """Run ``{name: fn}`` concurrently; returns {name: result}, raising
    on any worker error."""
    results, errors = {}, []

    def run(name, fn):
        try:
            results[name] = fn()
        except Exception as e:  # pragma: no cover - surfaced via assert
            errors.append((name, repr(e)))

    threads = [threading.Thread(target=run, args=(n, f))
               for n, f in workers.items()]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    return results


# -- reshard policy (pure functions) ----------------------------------------


class _FakePG:
    def __init__(self, ranks, rank):
        self._ranks = list(ranks)
        self.rank = rank


def test_reshard_alltoall_drops_dead_rows():
    pg = _FakePG([0, 2], rank=1)  # rank 1 died; I was original rank 2
    x = np.arange(12).reshape(3, 4)
    (out,), kw = dist._reshard_alltoall(pg, (x,), {}, [0, 1, 2])
    np.testing.assert_array_equal(out, x[[0, 2]])


def test_reshard_alltoallv_selects_rows_and_cols():
    pg = _FakePG([0, 2], rank=0)
    segs = [np.arange(2), np.arange(3), np.arange(4)]
    counts = np.arange(9).reshape(3, 3)
    (new_segs, new_counts), _ = dist._reshard_alltoallv(
        pg, (segs, counts), {}, [0, 1, 2])
    assert [s.size for s in new_segs] == [2, 4]
    np.testing.assert_array_equal(new_counts, counts[np.ix_([0, 2], [0, 2])])


def test_reshard_allgatherv_selects_counts():
    pg = _FakePG([1, 2], rank=0)
    x = np.arange(5)
    (out, counts), _ = dist._reshard_allgatherv(
        pg, (x, np.array([3, 5, 7])), {}, [0, 1, 2])
    np.testing.assert_array_equal(counts, [5, 7])
    np.testing.assert_array_equal(out, x)


def test_reshard_reduce_scatter_v_drops_dead_chunks():
    pg = _FakePG([0, 2], rank=0)
    counts = np.array([2, 3, 4])
    x = np.arange(9)
    (out, new_counts), _ = dist._reshard_reduce_scatter_v(
        pg, (x, counts), {}, [0, 1, 2])
    np.testing.assert_array_equal(new_counts, [2, 4])
    np.testing.assert_array_equal(out, np.concatenate([x[:2], x[5:9]]))


def test_reshard_scatter_trims_root_rows_only():
    x = np.arange(12).reshape(3, 4)
    root_pg = _FakePG([0, 2], rank=1)
    (out,), _ = dist._reshard_scatter(root_pg, (x,), {"root": 1}, [0, 1, 2])
    np.testing.assert_array_equal(out, x[[0, 2]])
    nonroot = _FakePG([0, 2], rank=0)
    tmpl = np.zeros(4)
    (out2,), _ = dist._reshard_scatter(nonroot, (tmpl,), {"root": 1},
                                       [0, 1, 2])
    np.testing.assert_array_equal(out2, tmpl)


# -- grow -------------------------------------------------------------------


def test_grow_admits_joiner_bitwise(sidecar_store):
    """Two members + one joiner: grow() splices the joiner into the
    ring under a fresh original id, the epoch bumps once, and an
    allreduce on the widened group is bitwise-correct with the joiner's
    contribution included."""
    n = 2
    store = sidecar_store(n)
    xs = [np.arange(6, dtype=np.int64) * (r + 1) for r in range(n + 1)]

    def member(rank):
        def fn():
            pg = dist.init_process_group(rank=rank, world_size=n,
                                         store_handle=store.handle,
                                         group_name="g1")
            try:
                out0 = pg.all_reduce(xs[rank])
                np.testing.assert_array_equal(out0, xs[0] + xs[1])
                # wait for the joiner's registration to appear, then grow
                deadline = time.monotonic() + 20
                while pg._client.try_get("pg/g1/join/slot/0") is None:
                    assert time.monotonic() < deadline, "joiner never came"
                    time.sleep(0.05)
                members = pg.grow(grace_s=2.0, timeout_s=20.0)
                assert members == [0, 1, 2]
                assert pg.epoch == 1 and pg.world_size == 3
                assert pg.rank == rank  # survivors keep their numbering
                out1 = pg.all_reduce(xs[rank])
                pg.barrier()
                return out1
            finally:
                pg.destroy(graceful=False)
        return fn

    def joiner():
        pg = dist.join_process_group(store_handle=store.handle,
                                     group_name="g1", timeout_s=40.0)
        try:
            assert pg.rank == 2 and pg.world_size == 3
            assert pg.global_ranks == [0, 1, 2]
            assert pg.epoch == 1
            out1 = pg.all_reduce(xs[2])
            pg.barrier()
            return out1
        finally:
            pg.destroy(graceful=False)

    res = _run_threads({0: member(0), 1: member(1), "j": joiner})
    want = xs[0] + xs[1] + xs[2]
    for who in (0, 1, "j"):
        np.testing.assert_array_equal(res[who], want)


def test_grow_without_joiners_is_noop(sidecar_store):
    n = 2
    store = sidecar_store(n)

    def fn(rank):
        def run():
            pg = dist.init_process_group(rank=rank, world_size=n,
                                         store_handle=store.handle,
                                         group_name="g2")
            try:
                members = pg.grow(grace_s=0.5, timeout_s=10.0)
                assert members == [0, 1]
                assert pg.epoch == 0  # no epoch burn on an empty grow
                out = pg.all_reduce(np.arange(4, dtype=np.int64))
                pg.barrier()
                return out
            finally:
                pg.destroy(graceful=False)
        return run

    res = _run_threads({0: fn(0), 1: fn(1)})
    np.testing.assert_array_equal(res[0], 2 * np.arange(4))


def test_second_grow_after_admission(sidecar_store):
    """A member admitted by one grow must rendezvous with the NEXT grow:
    the admit record carries the group's grow counter, so incumbents and
    the earlier joiner meet in one ``grow/g<N>`` namespace (a joiner
    keeping its own counter at 0 would split the rendezvous and time the
    whole group out — regression)."""
    n = 2
    store = sidecar_store(n)
    first_grown = threading.Event()
    xs = [np.arange(5, dtype=np.int64) * (r + 3) for r in range(n + 2)]

    def wait_key(pg, key):
        deadline = time.monotonic() + 30
        while pg._client.try_get(key) is None:
            assert time.monotonic() < deadline, f"{key} never appeared"
            time.sleep(0.05)

    def grow_both(pg):
        # the h/ key is the LAST registration write, so the leader's
        # candidate scan cannot race a half-registered joiner
        wait_key(pg, "pg/g5/join/h/0")
        assert pg.grow(grace_s=2.0, timeout_s=20.0) == [0, 1, 2]
        first_grown.set()
        wait_key(pg, "pg/g5/join/h/1")
        assert pg.grow(grace_s=2.0, timeout_s=20.0) == [0, 1, 2, 3]
        assert pg.epoch == 2 and pg.world_size == 4

    def member(rank):
        def fn():
            pg = dist.init_process_group(rank=rank, world_size=n,
                                         store_handle=store.handle,
                                         group_name="g5")
            try:
                grow_both(pg)
                out = pg.all_reduce(xs[rank])
                pg.barrier()
                return out
            finally:
                pg.destroy(graceful=False)
        return fn

    def joiner1():
        pg = dist.join_process_group(store_handle=store.handle,
                                     group_name="g5", timeout_s=40.0)
        try:
            assert pg.rank == 2 and pg.epoch == 1
            wait_key(pg, "pg/g5/join/h/1")
            assert pg.grow(grace_s=2.0, timeout_s=20.0) == [0, 1, 2, 3]
            assert pg.epoch == 2
            out = pg.all_reduce(xs[2])
            pg.barrier()
            return out
        finally:
            pg.destroy(graceful=False)

    def joiner2():
        assert first_grown.wait(60), "first grow never completed"
        pg = dist.join_process_group(store_handle=store.handle,
                                     group_name="g5", timeout_s=40.0)
        try:
            assert pg.rank == 3 and pg.world_size == 4 and pg.epoch == 2
            out = pg.all_reduce(xs[3])
            pg.barrier()
            return out
        finally:
            pg.destroy(graceful=False)

    res = _run_threads({0: member(0), 1: member(1),
                        "j1": joiner1, "j2": joiner2})
    want = xs[0] + xs[1] + xs[2] + xs[3]
    for who in (0, 1, "j1", "j2"):
        np.testing.assert_array_equal(res[who], want)


def test_grow_single_rank_without_store_raises():
    pg = dist.init_process_group(rank=0, world_size=1)
    try:
        with pytest.raises(RuntimeError, match="store"):
            pg.grow(timeout_s=2.0)
    finally:
        pg.destroy()


# -- warm spares ------------------------------------------------------------


def test_spare_promotion_preserves_world_size(sidecar_store):
    """Rank 1 dies mid-run on a group with one registered warm spare:
    the self-heal promotes the spare into original rank 1's identity —
    world size unchanged, epoch bumped once — and the interrupted
    collective retries exactly-once on the FULL-width group with the
    spare contributing in the dead rank's place."""
    n = 3
    store = sidecar_store(n)
    xs = [np.arange(6, dtype=np.int64) * (r + 1) for r in range(n)]
    want = xs[0] + xs[1] + xs[2]

    def member(rank):
        def fn():
            pg = dist.init_process_group(rank=rank, world_size=n,
                                         store_handle=store.handle,
                                         group_name="g3", plane="shm",
                                         self_heal=True)
            try:
                pg.start_watchdog(interval_s=0.3, timeout_s=2.5)
                out0 = pg.all_reduce(xs[rank])
                np.testing.assert_array_equal(out0, want)
                if rank == 1:
                    pg.stop_watchdog()
                    return "dead"
                out1 = pg.all_reduce(xs[rank], timeout_s=3.0)  # heals inside
                assert pg.epoch == 1
                assert pg.world_size == n          # promoted, not shrunk
                assert pg.global_ranks == [0, 1, 2]
                assert pg.wire_stats()["promotions"] >= 1
                pg.stop_watchdog()
                pg.barrier()
                return out1
            finally:
                pg.destroy(graceful=False)
        return fn

    def spare():
        pg = dist.init_process_group(world_size=n,
                                     store_handle=store.handle,
                                     group_name="g3", plane="shm",
                                     self_heal=True, spare=True)
        try:
            assert pg.is_standby
            with pytest.raises(RuntimeError, match="standby"):
                pg.all_reduce(np.zeros(2))  # spares sit out
            members = pg.wait_promotion(timeout_s=60.0)
            assert members == [0, 1, 2]
            assert pg.global_ranks[pg.rank] == 1  # adopted identity
            assert not pg.is_standby
            # join the survivors' transparent retry of the interrupted
            # collective, contributing in the dead rank's place
            out1 = pg.all_reduce(xs[1], timeout_s=15.0)
            pg.stop_watchdog()
            pg.barrier()
            return out1
        finally:
            pg.destroy(graceful=False)

    res = _run_threads({0: member(0), 1: member(1), 2: member(2),
                        "spare": spare})
    assert res[1] == "dead"
    for who in (0, 2, "spare"):
        np.testing.assert_array_equal(res[who], want)


def test_rooted_retry_sources_promoted_spare(sidecar_store):
    """PR 5 named-refused a rooted retry whose root died; with a warm
    spare the root's ORIGINAL identity survives the heal (the spare
    adopts it), so the retried broadcast sources from the promoted
    process instead of refusing."""
    n = 3
    store = sidecar_store(n)
    payload = np.arange(64, dtype=np.int64)

    def member(rank):
        def fn():
            pg = dist.init_process_group(rank=rank, world_size=n,
                                         store_handle=store.handle,
                                         group_name="g4", plane="shm",
                                         self_heal=True)
            try:
                pg.start_watchdog(interval_s=0.3, timeout_s=2.5)
                pg.barrier()
                if rank == 1:
                    pg.stop_watchdog()
                    return "dead"
                x = np.empty_like(payload)
                out = pg.broadcast(x, src=1, timeout_s=3.0)  # root died...
                assert pg.epoch == 1 and pg.world_size == n
                pg.stop_watchdog()
                pg.barrier()
                return out
            finally:
                pg.destroy(graceful=False)
        return fn

    def spare():
        pg = dist.init_process_group(world_size=n,
                                     store_handle=store.handle,
                                     group_name="g4", plane="shm",
                                     self_heal=True, spare=True)
        try:
            pg.wait_promotion(timeout_s=60.0)
            assert pg.global_ranks[pg.rank] == 1
            # ...long live the root: the spare sources the retry
            out = pg.broadcast(payload, src=pg.rank, timeout_s=15.0)
            pg.stop_watchdog()
            pg.barrier()
            return out
        finally:
            pg.destroy(graceful=False)

    res = _run_threads({0: member(0), 1: member(1), 2: member(2),
                        "spare": spare})
    assert res[1] == "dead"
    for who in (0, 2, "spare"):
        np.testing.assert_array_equal(res[who], payload)


# -- standby registry scan (prune must keep the dense walk intact) ----------


def test_registry_scan_survives_pruned_burned_slot(sidecar_store):
    """A promoted (burned + pruned) spare at slot 0 must not hide a
    live spare at slot 1 from a LATER heal: prune keeps the slot/admit
    keys — the dense first-missing-slot scan walks PAST the burned sid
    (skipped by its admit record), instead of stopping at a popped slot
    key and silently shrinking with a warm spare waiting."""
    store = sidecar_store(1)
    pg = dist.init_process_group(rank=0, world_size=1,
                                 store_handle=store.handle,
                                 group_name="g7")
    # a single-rank group skips the store client; the scan under test
    # only needs one, so attach it directly
    pg._client = bootstrap.BootstrapClient(store.handle, rank=0,
                                           scope="pg/g7/ring")
    spare1 = bootstrap.BootstrapClient(
        store.handle, rank=bootstrap.SPARE_RANK_BASE + 1,
        scope="pg/g7/ring")
    try:
        c = pg._client
        # slot 0: claimed, published, then promoted (admit) and pruned
        c.set("pg/g7/spares/slot/0", "tok0")
        c.set("pg/g7/spares/h/0", "stale-handle")
        c.set("pg/g7/spares/admit/0", "{}")
        # slot 1: a live, unburned spare heartbeating under its prefix id
        c.set("pg/g7/spares/slot/1", "tok1")
        c.set("pg/g7/spares/h/1", "live-handle")
        spare1.heartbeat()
        c.prune((), prefix="pg/g7/", spares=[0])
        # registry stays dense and burned: slot/admit kept, handle gone
        assert c.try_get("pg/g7/spares/slot/0") is not None
        assert c.try_get("pg/g7/spares/admit/0") is not None
        assert c.try_get("pg/g7/spares/h/0") is None
        # ...so the next heal's candidate scan still reaches slot 1
        assert pg._assign_spares([5], lambda: 10.0) == {5: (1,
                                                            "live-handle")}
        assert pg._pending_joiners(lambda: 10.0) == []
    finally:
        spare1.close()
        pg.destroy(graceful=False)


def test_suspend_p2p_rearms_resumed_streams():
    """A stream the resume service already served (state "resumed")
    must be RE-ARMED by the next membership change: its re-queued tail
    was fenced again with the new epoch, so a kept entry's state flag
    is cleared (wait/service re-run the resume protocol against the
    receiver's current cursor) — a stale "resumed" would let the tx
    wait flush an empty fresh wire and report the lost tail as sent.
    Dead peers' entries still drop."""
    pg = dist.init_process_group(rank=0, world_size=1)
    try:
        pg._p2p_inflight[(7, "tx", 0)] = {"seq": 0, "epoch": 0,
                                          "state": "resumed"}
        pg._p2p_inflight[(9, "tx", 0)] = {"seq": 0, "epoch": 0,
                                          "state": "resumed"}
        pg._suspend_p2p(members=[0, 7], fresh=frozenset())
        assert (9, "tx", 0) not in pg._p2p_inflight  # dead peer dropped
        assert "state" not in pg._p2p_inflight[(7, "tx", 0)]  # re-armed
        assert pg._p2p_resume_pending
    finally:
        pg.destroy()


# -- p2p stream resume ------------------------------------------------------


def test_p2p_streams_resume_across_heal(sidecar_store):
    """Survivor<->survivor p2p streams RESUME across a heal: pings posted
    before rank 1's death are epoch-fenced in flight, and the post-heal
    waits re-deliver them from the last fence-acknowledged frame instead
    of tearing the streams down (PR 5's named-refusal, widened)."""
    n = 3
    store = sidecar_store(n)
    ping = {0: np.arange(32, dtype=np.int64),
            2: np.arange(32, dtype=np.int64) * 7}

    def fn_rank(rank):
        def fn():
            pg = dist.init_process_group(rank=rank, world_size=n,
                                         store_handle=store.handle,
                                         group_name="g5", plane="shm",
                                         self_heal=True)
            try:
                pg.start_watchdog(interval_s=0.3, timeout_s=2.5)
                pg.barrier()
                if rank == 1:
                    pg.stop_watchdog()
                    return "dead"
                peer = 2 if rank == 0 else 0
                handles = pg.batch_isend_irecv([
                    ("recv", np.empty(32, np.int64), peer, 5),
                    ("send", ping[rank], peer, 5),
                ], timeout_s=20.0)
                # the collective aborts on rank 1's death and self-heals;
                # the in-flight ping frames to/from the SURVIVING peer are
                # fenced with the old epoch
                out = pg.all_reduce(np.ones(4, np.int64), timeout_s=3.0)
                np.testing.assert_array_equal(out, 2 * np.ones(4))
                assert pg.epoch == 1 and pg.global_ranks == [0, 2]
                heard = handles[0].wait()   # resumes, not raises
                handles[1].wait()
                np.testing.assert_array_equal(heard, ping[peer])
                stats = pg.wire_stats()
                assert stats["frames_resumed"] >= 1
                assert stats["frames_fenced"] >= 1
                pg.stop_watchdog()
                pg.barrier()
                return "resumed"
            finally:
                pg.destroy(graceful=False)
        return fn

    res = _run_threads({r: fn_rank(r) for r in range(n)})
    assert res[1] == "dead"
    assert res[0] == res[2] == "resumed"


def test_p2p_stream_to_dead_rank_still_raises_named(sidecar_store):
    """Resume is scoped to CONTINUOUS processes: a stream whose peer
    died (or whose slot was re-incarnated) still fails named — its data
    died with the process."""
    n = 3
    store = sidecar_store(n)

    def fn_rank(rank):
        def fn():
            pg = dist.init_process_group(rank=rank, world_size=n,
                                         store_handle=store.handle,
                                         group_name="g6", plane="shm",
                                         self_heal=True)
            try:
                pg.start_watchdog(interval_s=0.3, timeout_s=2.5)
                pg.barrier()
                if rank == 1:
                    # wire the 1->0 stream with one real message, then die
                    pg.send(np.arange(8, dtype=np.int64), 0, tag=3,
                            timeout_s=10.0)
                    pg.stop_watchdog()
                    return "dead"
                if rank == 0:
                    got = pg.recv(np.empty(8, np.int64), 1, tag=3,
                                  timeout_s=10.0)
                    np.testing.assert_array_equal(got, np.arange(8))
                    # a second in-flight recv the dead rank never feeds
                    h = pg.irecv(np.empty(8, np.int64), 1, tag=3,
                                 timeout_s=6.0)
                else:
                    h = None
                try:
                    pg.all_reduce(np.ones(4, np.int64), timeout_s=3.0)
                except (TimeoutError, OSError, RuntimeError):
                    pass  # rank 2 may lose the race to rewire; irrelevant
                if h is not None:
                    with pytest.raises((TimeoutError, OSError,
                                        RuntimeError)):
                        h.wait()
                pg.stop_watchdog()
                return "named"
            finally:
                pg.destroy(graceful=False)
        return fn

    res = _run_threads({r: fn_rank(r) for r in range(n)})
    assert res[1] == "dead"
    assert res[0] == "named"


def test_isend_queue_failure_leaves_no_stale_registration(sidecar_store):
    """An isend whose queue_send fails before a handle exists must not
    leak its resume registration or outstanding-slot claim: a leaked
    entry runs every later op on the stream uncovered, creeps the
    outstanding counter toward the seq-wrap refusal, and lets a later
    heal resume-resend a payload whose isend the caller watched FAIL."""
    n = 2
    store = sidecar_store(n)

    def fn_rank(rank):
        def fn():
            pg = dist.init_process_group(rank=rank, world_size=n,
                                         store_handle=store.handle,
                                         group_name="g8", plane="shm")
            try:
                pg.barrier()
                if rank == 1:
                    got = pg.recv(np.empty(8, np.int64), 0, tag=2,
                                  timeout_s=20.0)
                    np.testing.assert_array_equal(got, np.arange(8))
                    pg.barrier()
                    return "ok"
                # wire the 0->1 stream, then fail the NEXT queue_send
                pg.send(np.arange(8, dtype=np.int64), 1, tag=2,
                        timeout_s=20.0)
                wire = pg._p2p[(1, "tx")]
                orig_qs = wire.queue_send

                def boom(*a, **k):
                    raise RuntimeError("synthetic queue failure")

                wire.queue_send = boom
                with pytest.raises(RuntimeError, match="synthetic"):
                    pg.isend(np.arange(8, dtype=np.int64), 1, tag=7)
                wire.queue_send = orig_qs
                assert pg._p2p_inflight == {}  # no leaked resume slot
                # claim undone (stream keys carry the lane: chan 0 here)
                assert pg._p2p_seq[1][("out", "tx", 0, 7)] == 0
                pg.barrier()
                return "ok"
            finally:
                pg.destroy(graceful=False)
        return fn

    res = _run_threads({r: fn_rank(r) for r in range(n)})
    assert res[0] == res[1] == "ok"


def test_uncovered_op_interrupted_by_epoch_bump_raises():
    """The 'second outstanding op runs uncovered' contract must not
    become SILENT data loss on planes whose tx flush no-ops (shm): an
    uncovered op whose group epoch advanced mid-flight raises instead
    of reporting success for frames the fence dropped."""
    pg = dist.init_process_group(rank=0, world_size=1)
    try:
        pg._raise_if_interrupted(None, pg.epoch)  # quiescent: no raise
        with pytest.raises(OSError, match="membership change"):
            pg._raise_if_interrupted(None, pg.epoch - 1)
    finally:
        pg.destroy()
