"""The one-command first-contact runbook (rocnrdma_tpu.first_contact):
dryrun -> CLI smoke -> measured sweep -> provenance-honest merge -> step
alignment, end to end on the 8-device CPU oracle (VERDICT r3 next #5)."""

import json

from rocnrdma_tpu import first_contact


def test_first_contact_end_to_end(tmp_path, devices):
    outdir = tmp_path / "fc"
    rc = first_contact.main([
        "--outdir", str(outdir), "--platform", "cpu", "--fake-devices",
        "8", "--ranks", "8",
        # tiny grid: CI proves the chain, not the numbers
        "--smoke-size", "64K", "--sizes", "4K,64K",
        "--verbs", "allreduce,allgather",
        "--align-algo", "dtree", "--align-size", "1M"])
    report = [json.loads(l)
              for l in (outdir / "report.jsonl").read_text().splitlines()]
    steps = {r["step"]: r for r in report}
    # the chain ran in order with every step present (r5: step 0 is the
    # per-chip ladder/alpha calibration, VERDICT r4 missing #3)
    assert list(steps) == ["calibrate_chip", "dryrun", "cli_smoke",
                           "measured_sweep", "alltoall_scored",
                           "table_merge", "align_steps"]
    # the second contract metric rides the headline's discipline (r5):
    # median-of-trials + spread, persisted as its own artifact
    a2a = json.load(open(outdir / "alltoall_algbw.json"))
    assert a2a["metric"] == "alltoall_algbw_GBps_per_chip"
    assert a2a["stat"] == "median-of-trials" and a2a["value"] > 0
    assert a2a["spread"][0] <= a2a["value"] <= a2a["spread"][1]
    # calibrate + dryrun + smoke + sweep + merge must succeed on the
    # oracle; the alignment capture is thread-pool flaky there (the step
    # itself must still run and report honestly)
    for name in ("calibrate_chip", "dryrun", "cli_smoke", "measured_sweep",
                 "alltoall_scored", "table_merge"):
        assert steps[name]["ok"], steps[name]
    # the oracle's calibration artifact lands in OUTDIR (never the repo's
    # results/ — a fake-chip ladder must not shadow the real defaults),
    # carries the pairwise anchor, and round-trips through hw's reader
    cal_path = steps["calibrate_chip"]["artifact"]
    assert cal_path.startswith(str(outdir))
    cal = json.load(open(cal_path))
    assert "2" in cal["fold_ladder"] and cal["dispatch_alpha_s"] > 0
    assert rc == sum(1 for r in report if not r["ok"])
    # CLI smoke self-checked and wrote rows for all three CLIs
    smoke = [json.loads(l)
             for l in (outdir / "cli_smoke.jsonl").read_text().splitlines()]
    assert {r["collective"] for r in smoke} >= {"allreduce", "alltoall",
                                                "allgather"}
    # BASELINE rows carry busbw for every timed (verb, size, algo)
    base = [json.loads(l) for l in
            (outdir / "first_contact_baseline.jsonl").read_text().splitlines()]
    assert all(r["busbw_GBps"] > 0 for r in base)
    assert {r["collective"] for r in base} == {"allreduce", "allgather"}
    # the merged table is provenance-honest: measured rows over the model
    # table must be labeled mixed
    merged = json.load(open(outdir / "tuning_merged.json"))
    assert "mixed" in merged["_meta"]["provenance"]
    # ...and the measured winners supersede matching model keys
    measured = json.load(open(outdir / "tuning_measured.json"))
    for key in measured:
        if key != "_meta":
            assert merged[key] == measured[key]
