"""Quantized streaming collectives (ISSUE 13, DESIGN.md §5k): codec
round-trips, wire-level fp8/int8 streams, error feedback, the tuner's
compression pick, fault/chaos replay, and the moe-ffn convergence gate."""

import json
import threading

import numpy as np
import pytest

from rocnrdma_tpu import native
from rocnrdma_tpu.metrics import WIRE
from rocnrdma_tpu.transport import (
    HostQPNet,
    TCPNet,
    ring_allgather_over_net,
    ring_allreduce_over_net,
    ring_reduce_scatter_over_net,
)
from rocnrdma_tpu.transport import codec as C
from rocnrdma_tpu.transport import lanes as _lanes
from rocnrdma_tpu.transport import tuner

needs_native = pytest.mark.skipif(
    not native.available(), reason="native library not buildable")

FLOAT_DTYPES = [np.float16, np.float32, np.float64]


# ---------------------------------------------------------------------------
# Codec unit round-trips + edge cases
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", ["int8", "fp8"])
@pytest.mark.parametrize("dtype", FLOAT_DTYPES)
def test_roundtrip_all_float_dtypes(name, dtype):
    codec = C.get(name)
    x = np.random.default_rng(0).standard_normal(4097).astype(dtype)
    enc = bytes(codec.encode(x))
    assert len(enc) == codec.encoded_nbytes(x.nbytes, x.dtype.itemsize)
    dest = np.empty(x.nbytes, np.uint8)
    n = codec.decode_fold(np.frombuffer(enc, np.uint8), dest, dtype, None)
    assert n == x.nbytes
    d = dest.view(dtype)
    # bounded worst-case error: int8's step is absolute (scale/2, and
    # the pow2 scale is at most 2*maxabs/127); fp8-e4m3's rounding is
    # relative (3 mantissa bits -> 2^-4 of the value, so 2^-4 of
    # maxabs worst-case); the slack absorbs f16 input rounding
    rel = {"int8": 2.0 / 127, "fp8": 1.0 / 16}[name]
    assert float(np.abs(d.astype(np.float64)
                        - x.astype(np.float64)).max()) <= \
        1.01 * rel * float(np.abs(x.astype(np.float64)).max()) + 1e-12


@pytest.mark.parametrize("name", ["int8", "fp8"])
def test_roundtrip_idempotent_and_commit_matches_decode(name):
    codec = C.get(name)
    x = np.random.default_rng(1).standard_normal(50000).astype(np.float32)
    v = x.copy()
    enc = bytes(codec.encode(v, commit=v))        # v becomes the image
    dest = np.empty(x.nbytes, np.uint8)
    codec.decode_fold(np.frombuffer(enc, np.uint8), dest, np.float32, None)
    # the committed local image IS what a receiver decodes
    np.testing.assert_array_equal(v, dest.view(np.float32))
    # re-encoding the decoded image is byte-identical (the pow2-scale
    # idempotency rule — what makes allgather-phase forwards lossless)
    assert bytes(codec.encode(v.copy())) == enc


def test_int8_roundtrip_equals_decode_of_encode():
    codec = C.get("int8")
    x = np.random.default_rng(2).standard_normal(10000).astype(np.float32)
    enc = bytes(codec.encode(x))
    dest = np.empty(x.nbytes, np.uint8)
    codec.decode_fold(np.frombuffer(enc, np.uint8), dest, np.float32, None)
    np.testing.assert_array_equal(codec.roundtrip(x),
                                  dest.view(np.float32))


def test_zero_frame_encodes_scale_zero_and_decodes_zeros():
    codec = C.get("int8")
    x = np.zeros(1000, np.float32)
    enc = bytes(codec.encode(x))
    assert np.frombuffer(enc[:4], "<f4")[0] == 0.0
    dest = np.full(x.nbytes, 0xFF, np.uint8)
    codec.decode_fold(np.frombuffer(enc, np.uint8), dest, np.float32, None)
    np.testing.assert_array_equal(dest.view(np.float32), x)
    # zeros genuinely FOLD (a max against zeros is not a no-op)
    d2 = (-np.ones(1000, np.float32)).view(np.uint8).copy()
    codec.decode_fold(np.frombuffer(enc, np.uint8), d2, np.float32,
                      np.maximum)
    np.testing.assert_array_equal(d2.view(np.float32), np.zeros(1000))


@pytest.mark.parametrize("bad", [np.inf, -np.inf, np.nan])
def test_nonfinite_refusal_is_named(bad):
    codec = C.get("int8")
    x = np.ones(64, np.float32)
    x[13] = bad
    with pytest.raises(ValueError, match="non-finite"):
        codec.encode(x)
    with pytest.raises(ValueError, match="non-finite"):
        codec.roundtrip(x)
    q = np.empty_like(x)
    with pytest.raises(ValueError, match="non-finite"):
        codec.ef_update(x, None, q, np.empty_like(x))


def test_frame_shape_mismatch_refuses_named():
    codec = C.get("int8")
    enc = bytearray(bytes(codec.encode(np.ones(100, np.float32))))
    dest = np.empty(400, np.uint8)
    # header says 100 elems but the wire frame is short
    with pytest.raises(ValueError, match="mismatch"):
        codec.decode_fold(np.frombuffer(bytes(enc[:50]), np.uint8),
                          dest, np.float32, None)
    with pytest.raises(ValueError, match="short frame"):
        codec.decode_fold(np.frombuffer(b"\x00" * 4, np.uint8), dest,
                          np.float32, None)


def test_pow2_scale_discipline():
    # the scale is always a power of two with maxabs/scale <= qmax
    import math
    for maxabs in (1e-30, 0.1, 1.0, 3.7, 127.0, 1e20):
        s = C._pow2_scale(maxabs, 127.0)
        m, _e = math.frexp(s)
        assert m == 0.5  # exact power of two
        assert maxabs / s <= 127.0
    assert C._pow2_scale(0.0, 127.0) == 0.0


def test_unknown_codec_and_auto_validation():
    with pytest.raises(ValueError, match="unknown codec"):
        C.get("zstd")
    assert C.validate_name(None) is None
    assert C.validate_name("auto") == "auto"
    assert C.validate_name("int8") == "int8"
    with pytest.raises(ValueError, match="unknown codec"):
        C.validate_name("bf4")


# ---------------------------------------------------------------------------
# The tuner's compression pick (pure, per plane)
# ---------------------------------------------------------------------------


def test_pick_codec_off_on_shm_on_for_tcp():
    """The ISSUE-13 committed-seed verdict: compression loses where
    beta is cheap (shm) and wins on the slow tcp leg — and the pick is
    a pure function (same inputs, same answer, twice)."""
    shm = tuner.host_wire_model("shm")
    tcp = tuner.host_wire_model("tcp")
    for size in (256 << 10, 1 << 20, 8 << 20):
        assert shm.pick_codec(size, 4) is None
        assert tcp.pick_codec(size, 4) == "int8"
        assert tcp.pick_codec(size, 4) == tcp.pick_codec(size, 4)


def test_hop_time_codec_arm_prices_wire_and_cpu():
    m = tuner.HostWireModel("t")
    plain = m.hop_time(1 << 20, 1 << 19, 2)
    comp = m.hop_time(1 << 20, 1 << 19, 2, codec=(4, 1.0, C.HDR))
    # the compressed arm's wire term shrank but its CPU term exists:
    # both effects must be visible in the price
    p = m.params
    assert comp < plain  # seed beta 2.5e-9 > codec 1.3e-9: wins
    assert comp > plain - (1 << 20) * p.beta_s_per_b  # CPU not free


# ---------------------------------------------------------------------------
# Wire-level quantized streams (in-process rings, both planes)
# ---------------------------------------------------------------------------


def _run_ring(net_cls, n, fn, codec=None, timeout=120):
    net = net_cls()
    net.init()
    lane = (net.open_lane("quant", codec=codec) if codec
            else net.lanes.by_name("default"))
    handles, listens = [], []
    for _ in range(n):
        h, l = net.listen()
        handles.append(h)
        listens.append(l)
    results: list = [None] * n
    errors: list = []

    def worker(rank):
        try:
            s = net.connect(0, handles[(rank + 1) % n])
            r = net.accept(listens[rank])
            with _lanes.lane_context(lane.id):
                results[rank] = fn(net, s, r, rank)
        except Exception as e:  # pragma: no cover - surfaced via assert
            import traceback
            traceback.print_exc()
            errors.append((rank, e))

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout)
    assert not errors, errors
    net.close()
    return results


@needs_native
@pytest.mark.parametrize("name", ["int8", "fp8"])
@pytest.mark.parametrize("n", [2, 3])
def test_quantized_allreduce_tolerance_and_cross_rank_bitwise(name, n):
    xs = [np.random.default_rng(r).standard_normal(70001)
          .astype(np.float32) for r in range(n)]
    base = WIRE.snapshot()
    res = _run_ring(HostQPNet, n,
                    lambda net, s, r, rank: ring_allreduce_over_net(
                        net, s, r, xs[rank], rank, n), codec=name)
    d = WIRE.delta(base)
    want = np.sum(xs, axis=0)
    rel = {"int8": 2.0 / 127, "fp8": 1.0 / 8}[name]
    tol = rel * (n + 1) * float(np.abs(want).max())
    for r in range(n):
        assert float(np.abs(res[r] - want).max()) <= tol
    # every rank lands the SAME bits (§5k's cross-rank rule: fold hops
    # commit their quantized image before forwarding)
    for r in range(1, n):
        np.testing.assert_array_equal(res[0], res[r])
    # the codec was genuinely on the wire, with zero staging copies
    assert d["frames_encoded"] > 0
    assert d["payload_bytes_saved"] > 0
    assert d["payload_bytes_copied"] == 0


@needs_native
def test_quantized_allreduce_on_tcp_plane():
    n = 2
    xs = [np.random.default_rng(r).standard_normal(50000)
          .astype(np.float32) for r in range(n)]
    res = _run_ring(TCPNet, n,
                    lambda net, s, r, rank: ring_allreduce_over_net(
                        net, s, r, xs[rank], rank, n), codec="int8")
    want = np.sum(xs, axis=0)
    assert np.allclose(res[0], want, rtol=0.05,
                       atol=0.05 * float(np.abs(want).max()))
    np.testing.assert_array_equal(res[0], res[1])


@needs_native
def test_quantized_reduce_scatter_and_allgather():
    n = 3
    xs = [np.random.default_rng(10 + r).standard_normal(30001)
          .astype(np.float32) for r in range(n)]
    rs = _run_ring(HostQPNet, n,
                   lambda net, s, r, rank: ring_reduce_scatter_over_net(
                       net, s, r, xs[rank], rank, n), codec="int8")
    want = np.sum(xs, axis=0)
    bounds = [len(want) * i // n for i in range(n + 1)]
    for r in range(n):
        seg = want[bounds[r]:bounds[r + 1]]
        assert np.allclose(rs[r], seg, rtol=0.05,
                           atol=0.05 * float(np.abs(want).max()))
    ag = _run_ring(HostQPNet, n,
                   lambda net, s, r, rank: ring_allgather_over_net(
                       net, s, r, xs[rank], rank, n), codec="int8")
    stacked = np.stack(xs)
    for r in range(n):
        assert np.allclose(ag[r], stacked, rtol=0.05,
                           atol=0.05 * float(np.abs(stacked).max()))


@needs_native
def test_quantized_lg_path_big_frames():
    """A hop big enough that even the ENCODED frame rides the LG put
    path (decode-and-fold straight out of the arena view)."""
    n = 2
    elems = (12 << 20) // 4  # 12 MiB buffers -> >= 3 MiB encoded posts
    xs = [np.random.default_rng(r).standard_normal(elems)
          .astype(np.float32) for r in range(n)]
    base = WIRE.snapshot()
    res = _run_ring(HostQPNet, n,
                    lambda net, s, r, rank: ring_allreduce_over_net(
                        net, s, r, xs[rank], rank, n), codec="int8")
    d = WIRE.delta(base)
    want = xs[0] + xs[1]
    assert np.allclose(res[0], want, rtol=0.05,
                       atol=0.05 * float(np.abs(want).max()))
    np.testing.assert_array_equal(res[0], res[1])
    assert d["frames_encoded"] > 0
    assert d["payload_bytes_copied"] == 0


@needs_native
def test_non_float_dtype_passes_through_bitwise():
    """The shared-dtype rule: int payloads ride a codec lane
    UNCOMPRESSED on both ends — the chaos tasks' int64 bitwise oracle
    holds even on a quantized lane."""
    n = 2
    xs = [np.random.default_rng(r).integers(-10**6, 10**6, 20000)
          for r in range(n)]
    base = WIRE.snapshot()
    res = _run_ring(HostQPNet, n,
                    lambda net, s, r, rank: ring_allreduce_over_net(
                        net, s, r, xs[rank], rank, n), codec="int8")
    d = WIRE.delta(base)
    np.testing.assert_array_equal(res[0], xs[0] + xs[1])
    np.testing.assert_array_equal(res[1], xs[0] + xs[1])
    assert d["frames_encoded"] == 0  # genuinely passed through


@needs_native
def test_codec_lane_negotiation_gauge_and_auto():
    """The negotiated codec rides the wire gauge; 'auto' resolves
    through the committed model per plane — None on shm, so the gauge
    reads uncompressed even though the lane asked 'auto'."""
    n = 2
    xs = [np.random.default_rng(r).standard_normal(70000)
          .astype(np.float32) for r in range(n)]
    _run_ring(HostQPNet, n,
              lambda net, s, r, rank: ring_allreduce_over_net(
                  net, s, r, xs[rank], rank, n), codec="int8")
    assert WIRE.negotiation()["codec"] == "int8"
    _run_ring(HostQPNet, n,
              lambda net, s, r, rank: ring_allreduce_over_net(
                  net, s, r, xs[rank], rank, n), codec="auto")
    assert WIRE.negotiation()["codec"] is None  # shm: beta is cheap


def test_stale_payload_stash_cannot_cross_streams():
    """Review hardening: the EF layer's pre-built hop-0 payload dies
    with the stream it was issued for. A paced codec lane forces a
    MULTI-frame hop 0 (the stash cannot be used); a later single-frame
    collective of the same (size, dtype) on another codec lane must
    re-encode its OWN data, not ship the previous collective's
    bytes."""
    from rocnrdma_tpu import distributed as dist
    from rocnrdma_tpu.transport import bootstrap

    n = 2
    elems = 262144  # 1 MiB fp32
    store = bootstrap.BootstrapServer(n_ranks=n)
    outs = [None] * n
    errors = []

    def worker(rank):
        pg = None
        try:
            pg = dist.init_process_group(rank=rank, world_size=n,
                                         store_handle=store.handle)
            paced = pg.channel("quant-paced", codec="int8",
                               credit_bytes=262144)
            plain = pg.channel("quant-plain", codec="int8")
            x = (np.random.default_rng(rank).standard_normal(elems)
                 .astype(np.float32))
            block = (np.random.default_rng(100 + rank)
                     .standard_normal(elems).astype(np.float32))
            # sum allreduce: EF stashes the whole-buffer payload, but
            # the credit-capped frame splits hop 0 into several frames
            # — the stash must die unused with this stream
            paced.all_reduce(x, timeout_s=60.0)
            # same total bytes, single frame, same dtype: the stale
            # stash would have matched byte-for-byte pre-fix
            outs[rank] = (plain.all_gather(block, timeout_s=60.0), block)
        except Exception as e:  # pragma: no cover
            import traceback
            traceback.print_exc()
            errors.append((rank, e))
        finally:
            if pg is not None:
                pg.destroy()

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    store.close()
    assert not errors, errors
    blocks = [outs[r][1] for r in range(n)]
    for r in range(n):
        got = outs[r][0]
        for src in range(n):
            # the allgather's rows are ITS OWN quantized blocks —
            # a stale-stash delivery would land the allreduce's sum
            assert np.allclose(
                got[src], blocks[src], rtol=0.05,
                atol=0.05 * float(np.abs(blocks[src]).max())), (r, src)


def test_channel_partial_restatement_adopts_unstated_knobs():
    """Review hardening: restating SOME lane knobs conflicts only on
    what the caller said — unstated ones adopt the open lane's values
    (the bucket knobs' adopt-while-unset contract, extended to
    codec)."""
    from rocnrdma_tpu import distributed as dist
    from rocnrdma_tpu.transport import bootstrap

    store = bootstrap.BootstrapServer(n_ranks=1)
    pg = dist.init_process_group(rank=0, world_size=1,
                                 store_handle=store.handle)
    try:
        pg.channel("g", priority=3, codec="int8")
        pg.channel("g", priority=3)        # codec unstated: adopted
        pg.channel("g", codec="int8")      # priority unstated: adopted
        pg.channel("g")                    # pure fetch
        with pytest.raises(ValueError, match="conflicting re-open"):
            pg.channel("g", codec="fp8")   # a REAL conflict still refuses
    finally:
        pg.destroy()
        store.close()


def test_lane_codec_conflict_refused():
    reg = _lanes.LaneRegistry()
    reg.open("q", codec="int8")
    reg.open("q", codec="int8")  # idempotent
    with pytest.raises(ValueError, match="conflicting re-open"):
        reg.open("q", codec="fp8")
    with pytest.raises(ValueError, match="conflicting re-open"):
        reg.open("q")  # codec=None restatement conflicts too


# ---------------------------------------------------------------------------
# Error feedback: residual store determinism + epoch reset
# ---------------------------------------------------------------------------


def test_residual_feedback_semantics_and_determinism():
    codec = C.get("int8")
    store = C.ResidualStore()
    x = np.random.default_rng(3).standard_normal(40000).astype(np.float32)
    key = (0, "all_reduce", x.shape, "float32")
    q1, r1 = store.feedback(key, x, 0, codec)
    # literally residual = x - decode(encode(x)) on a fresh key
    np.testing.assert_array_equal(q1, codec.roundtrip(x))
    np.testing.assert_allclose(r1, x - q1, rtol=0, atol=0)
    # an aborted attempt commits nothing: the same call repeats bitwise
    q1b, r1b = store.feedback(key, x, 0, codec)
    np.testing.assert_array_equal(q1, q1b)
    np.testing.assert_array_equal(r1, r1b)
    r1_copy = np.array(r1, copy=True)
    q1_copy = np.array(q1, copy=True)
    store.commit(key, 0, r1_copy, q=q1_copy)
    # the carried residual folds into the next round's send
    q2, _r2 = store.feedback(key, x, 0, codec)
    np.testing.assert_array_equal(q2, codec.roundtrip(x + r1))
    # EF is unbiased over rounds: the mean of committed values tracks x
    # far tighter than a single quantization
    acc = np.zeros_like(x)
    res = None
    for _ in range(32):
        q, res = store.feedback(key, x, 0, codec)
        store.commit(key, 0, np.array(res, copy=True),
                     q=np.array(q, copy=True))
        acc += q
    ef_err = float(np.abs(acc / 32 - x).max())
    one_shot = float(np.abs(codec.roundtrip(x) - x).max())
    assert ef_err < 0.25 * one_shot


def test_residual_epoch_reset_is_deterministic_and_digested():
    codec = C.get("int8")
    x = np.random.default_rng(4).standard_normal(1000).astype(np.float32)
    key = (0, "all_reduce", x.shape, "float32")

    def run():
        store = C.ResidualStore()
        q, r = store.feedback(key, x, 0, codec)
        store.commit(key, 0, np.array(r, copy=True),
                     q=np.array(q, copy=True))
        # the heal bumped the epoch: the key resets to zero residual,
        # deterministically — q after the reset equals the fresh-key q
        q2, r2 = store.feedback(key, x, 1, codec)
        np.testing.assert_array_equal(q2, codec.roundtrip(x))
        store.commit(key, 1, np.array(r2, copy=True),
                     q=np.array(q2, copy=True))
        return store.digest()

    assert run() == run()  # digest-pinned across two identical runs


def test_residual_cap_evicts_oldest():
    codec = C.get("int8")
    store = C.ResidualStore(cap=2)
    x = np.ones(10, np.float32)
    for i in range(3):
        key = (i, "all_reduce", x.shape, "float32")
        q, r = store.feedback(key, x, 0, codec)
        store.commit(key, 0, r, q=q)
    with store._lock:
        assert len(store._entries) == 2
        assert (0, "all_reduce", x.shape, "float32") not in store._entries


# ---------------------------------------------------------------------------
# FaultNet: codec frames under injected faults, replay-equal
# ---------------------------------------------------------------------------


@needs_native
def test_faultnet_codec_lane_delay_replay_equal():
    """The per-channel codec fault test: delayed completions injected
    against the quantized lane BY NAME — the decode still lands the
    bytes at true delivery, so two same-seed runs produce bitwise-equal
    results AND equal injection fingerprints, with the codec provably
    engaged."""
    from rocnrdma_tpu.transport.faults import FaultNet, FaultSchedule

    n = 2
    xs = [np.random.default_rng(20 + r).standard_normal(60000)
          .astype(np.float32) for r in range(n)]

    def one_run():
        net = FaultNet(HostQPNet(), FaultSchedule(
            23, 0, chan_test_delay_p={"quant": 0.7},
            test_delay_polls=(1, 3)))
        net.init()
        lane = net.open_lane("quant", codec="int8")
        handles, listens = [], []
        for _ in range(n):
            h, l = net.listen()
            handles.append(h)
            listens.append(l)
        results = [None] * n
        errors = []

        def worker(rank):
            try:
                s = net.connect(0, handles[(rank + 1) % n])
                r = net.accept(listens[rank])
                with _lanes.lane_context(lane.id):
                    results[rank] = ring_allreduce_over_net(
                        net, s, r, xs[rank], rank, n)
            except Exception as e:  # pragma: no cover
                errors.append((rank, e))

        threads = [threading.Thread(target=worker, args=(r,))
                   for r in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        fp = net.schedule.fingerprint()
        delayed = net.schedule.counters.counts.get("chan-test-delayed", 0)
        net.close()
        return results, fp, delayed

    (res_a, fp_a, delayed_a) = one_run()
    (res_b, fp_b, delayed_b) = one_run()
    assert delayed_a > 0  # faults genuinely landed on the codec lane
    assert fp_a == fp_b
    for a, b in zip(res_a, res_b):
        np.testing.assert_array_equal(a, b)  # bitwise replay-equal
    want = xs[0] + xs[1]
    assert np.allclose(res_a[0], want, rtol=0.05,
                       atol=0.05 * float(np.abs(want).max()))


@needs_native
def test_kill_and_heal_codec_replay_equal_and_residual_reset():
    """The codec x heal acceptance run (ISSUE 13): kill-and-heal chaos
    with the round allreduces on a quantized int8 lane (error feedback
    ON, float payloads). Asserted: survivors heal to epoch 1 with
    frames fenced, every committed round is inside the codec's
    analytic tolerance, and two same-seed runs print identical
    FAULTLOG/HEALLOG/FLEET digests AND identical CODECLOG lines — the
    CODECLOG digests every committed quantized result plus the
    error-feedback residual state, so the deterministic post-heal
    residual reset is replay-pinned, not just claimed."""
    from rocnrdma_tpu.runtime.multiprocess import run_workers

    def _line(r, key):
        for line in r.stdout.splitlines():
            if line.startswith(key + " "):
                return line[len(key) + 1:]
        raise AssertionError(f"{key} missing from rank {r.process_id}:\n"
                             f"{r.stdout}")

    n, seed, rounds, victim = 4, 11, 6, 2
    runs = [run_workers(n, "kill-and-heal", timeout_s=150.0, seed=seed,
                        rounds=rounds, kill_ranks=str(victim),
                        kill_ops="49", codec="int8") for _ in range(2)]
    for results in runs:
        rc = {r.process_id: r.returncode for r in results}
        assert rc[victim] == 7, results[victim].stdout
        for r in results:
            assert r.returncode != -9, \
                f"rank {r.process_id} HUNG:\n{r.stderr}"
            if r.process_id == victim:
                continue
            assert r.returncode == 0, \
                f"survivor {r.process_id} exited {r.returncode}:\n" \
                f"{r.stdout}\n{r.stderr}"
            assert _line(r, "EPOCH") == "1"
            assert _line(r, "MEMBERS") == "[0, 1, 3]"
        assert sum(int(_line(r, "FENCED")) for r in results
                   if r.process_id != victim) > 0
    for a, b in zip(*runs):
        if a.process_id == victim:
            continue
        assert _line(a, "FAULTLOG") == _line(b, "FAULTLOG"), a.process_id
        assert _line(a, "HEALLOG") == _line(b, "HEALLOG"), a.process_id
        assert _line(a, "FLEET") == _line(b, "FLEET"), a.process_id
        assert _line(a, "CODECLOG") == _line(b, "CODECLOG"), a.process_id


# ---------------------------------------------------------------------------
# The convergence gate: the flagship moe-ffn train step, quantized wire
# with error feedback vs the fp32 wire.
# ---------------------------------------------------------------------------


@needs_native
def test_moe_ffn_convergence_with_error_feedback(sidecar_2):
    """Data-parallel training of the flagship moe-ffn expert
    (workloads.moe.ffn_expert: two einsums + gelu — the step the MFU
    profile counts) over a REAL 2-rank shm host wire: per-rank jax
    grads, gradient allreduce on (a) the fp32 wire and (b) an int8
    codec lane with error feedback, plus (c) the int8 lane on the
    HIERARCHICAL schedule (node_of=[0, 1] — every rank a node leader,
    the gradient allreduce riding the cross-node leg whose RS-phase
    partial sum feeds the ISSUE-14 hier-xleg residual). Both quantized
    trajectories must hold the fp32 loss trajectory within tolerance —
    the acceptance gate that error feedback preserves convergence with
    the hierarchical+codec path active too."""
    import jax
    import jax.numpy as jnp

    from rocnrdma_tpu import distributed as dist
    from rocnrdma_tpu.workloads.moe import ffn_expert

    E, cap, d, ffn = 2, 8, 16, 32
    steps, lr, n = 24, 0.05, 2
    rng = np.random.default_rng(7)
    w_in0 = (rng.standard_normal((E, d, ffn)) * 0.3).astype(np.float32)
    w_out0 = (rng.standard_normal((E, ffn, d)) * 0.3).astype(np.float32)
    # a fixed target expert the trainee must imitate (a well-posed,
    # steadily-decreasing loss)
    tw_in = (rng.standard_normal((E, d, ffn)) * 0.5).astype(np.float32)
    tw_out = (rng.standard_normal((E, ffn, d)) * 0.5).astype(np.float32)
    target = ffn_expert(jnp.asarray(tw_in), jnp.asarray(tw_out))

    def loss_fn(params, x):
        y = ffn_expert(params[0], params[1])(x)
        return jnp.mean((y - target(x)) ** 2)

    grad_fn = jax.jit(jax.value_and_grad(loss_fn))

    def batch(rank, step):
        return jnp.asarray(np.random.default_rng((rank, step))
                           .standard_normal((E, cap, d))
                           .astype(np.float32))

    def train(pg, surface, algorithm=None):
        w_in = jnp.asarray(w_in0)
        w_out = jnp.asarray(w_out0)
        losses = []
        for step in range(steps):
            loss, (g_in, g_out) = grad_fn((w_in, w_out),
                                          batch(pg.rank, step))
            flat = np.concatenate([np.asarray(g_in).ravel(),
                                   np.asarray(g_out).ravel()])
            summed = surface.all_reduce(flat, op="avg",
                                        algorithm=algorithm)
            g_in = summed[:g_in.size].reshape(g_in.shape)
            g_out = summed[g_in.size:].reshape(g_out.shape)
            w_in = w_in - lr * g_in
            w_out = w_out - lr * g_out
            # the fleet loss (metric only — rides the default fp32
            # lane so the metric never quantizes)
            losses.append(float(pg.all_reduce(
                np.array([float(loss)]), op="avg")[0]))
        return losses

    def worker(rank, store_handle, mode, out):
        pg = None
        try:
            pg = dist.init_process_group(
                rank=rank, world_size=n, store_handle=store_handle,
                group_name=f"conv-{mode}", plane="shm",
                node_of=[0, 1] if mode == "hier-int8" else None)
            surface = (pg.channel("quant", codec="int8")
                       if mode != "fp32" else pg)
            out[rank] = train(pg, surface,
                              algorithm="hier" if mode == "hier-int8"
                              else None)
        finally:
            if pg is not None:
                pg.destroy()

    trajectories = {}
    for mode in ("fp32", "int8", "hier-int8"):
        store = sidecar_2(n)
        outs = [None] * n
        threads = [threading.Thread(target=worker,
                                    args=(r, store.handle, mode, outs))
                   for r in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert all(o is not None for o in outs), outs
        # both ranks saw the same fleet loss (params stayed in sync —
        # the cross-rank-bitwise wire rule doing its job)
        np.testing.assert_allclose(outs[0], outs[1], rtol=1e-6)
        trajectories[mode] = np.asarray(outs[0])

    f = trajectories["fp32"]
    assert f[-1] < f[0] * 0.7  # the fp32 baseline genuinely trains
    for mode in ("int8", "hier-int8"):
        q = trajectories[mode]
        assert q[-1] < q[0] * 0.7, mode  # the quantized wire trains too
        # error feedback holds the loss trajectory within tolerance of
        # the fp32 wire at every step — flat AND hierarchical
        rel = np.abs(q - f) / np.maximum(1e-8, f)
        assert float(rel.max()) < 0.15, (mode, rel.max(),
                                         list(zip(f, q)))


@pytest.fixture
def sidecar_2():
    from rocnrdma_tpu.transport import bootstrap
    servers = []

    def factory(n):
        s = bootstrap.BootstrapServer(n_ranks=n)
        servers.append(s)
        return s
    yield factory
    for s in servers:
        s.close()


# ---------------------------------------------------------------------------
# The committed artifact (results/codec_r01.json) schema + fixed point
# ---------------------------------------------------------------------------


def test_committed_codec_record_schema():
    import os
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "results", "codec_r01.json")
    with open(path) as fp:
        doc = json.load(fp)
    assert doc["schema"] == "codec_r01"
    floors = doc["floors"]
    assert floors["codec_min_x"] == 1.5
    assert floors["fp32_floor_GBps"] > 0
    algos = [r["algo"] for r in doc["records"]]
    assert "ring" in algos and "codec-int8" in algos \
        and "codec-fp8" in algos
    int8 = next(r for r in doc["records"] if r["algo"] == "codec-int8")
    cx = int8["extra"]["codec"]
    # the committed capability: the int8 wire's best trial beat the
    # fp32 floor by the acceptance multiple, with real savings and a
    # measured (bounded) value-space cost
    assert cx["floor_x_best"] >= floors["codec_min_x"]
    assert cx["bytes_saved"] > 0
    assert 0 < cx["max_abs_err"] <= \
        floors["max_abs_err_ceil"]["int8"]
    assert int8["extra"]["wire"]["codec"] == "int8"
    assert int8["extra"]["wire"]["payload_bytes_copied"] == 0


def test_sentinel_codec_floor_fixed_point():
    """The committed codec records pass their own sentinel floor (the
    all-zero-ratchet fixed point every committed artifact holds)."""
    import os

    from tools import sentinel
    path = os.path.join(sentinel.RESULTS, "codec_r01.json")
    with open(path) as fp:
        rows = json.load(fp)["records"]
    assert sentinel.check_codec_floor(rows) == []
    # ...and a doctored regression IS caught
    import copy
    bad = copy.deepcopy(rows)
    for r in bad:
        co = r.get("extra", {}).get("codec")
        if co:
            co["floor_x_best"] = 1.0
    assert sentinel.check_codec_floor(bad), \
        "a sub-floor codec row must be a finding"
