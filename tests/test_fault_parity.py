"""FaultNet verb parity, checked on the LIVE classes (the runtime twin of
the AST conformance pass in tools/analyze/vtable.py): every public verb of
the canonical shm-plane vtable must be defined DIRECTLY on FaultNet — a
verb that falls through FaultNet.__getattr__ runs with zero fault
injection, which is how the one-sided put path shipped uncovered in PR 2.
A NEW verb added to HostQPNet fails here loudly until faults cover it."""

import inspect

from rocnrdma_tpu.transport import plugin
from rocnrdma_tpu.transport.faults import FaultNet, FaultSchedule


def _public_verbs(cls) -> dict:
    """name -> function, public callables across the mro (vtable surface)."""
    out = {}
    for klass in reversed(cls.__mro__):
        for name, val in vars(klass).items():
            if name.startswith("_"):
                continue
            if callable(val) or isinstance(val, staticmethod):
                out[name] = val
    return out


def _wrapped_verbs() -> set:
    """What FaultNet defines ITSELF — __getattr__ delegation excluded by
    construction (vars() sees only the class body)."""
    return {n for n, v in vars(FaultNet).items()
            if not n.startswith("_") and callable(v)}


def test_faultnet_wraps_the_full_live_vtable():
    canon = set(_public_verbs(plugin.HostQPNet))
    missing = canon - _wrapped_verbs()
    assert not missing, (
        f"FaultNet does not wrap {sorted(missing)} — these verbs fall "
        f"through __getattr__ to the inner net and run WITHOUT fault "
        f"injection; wrap them (even as explicit passthroughs) before "
        f"shipping")


def test_tcp_plane_carries_the_full_live_vtable():
    canon = _public_verbs(plugin.HostQPNet)
    tcp = _public_verbs(plugin.TCPNet)
    missing = set(canon) - set(tcp)
    assert not missing, f"TCPNet is missing vtable verbs {sorted(missing)}"


def test_wrapped_signatures_accept_canonical_calls():
    """Every FaultNet verb must accept a call shaped like the canon's
    signature: same required params (wrapper *args/**kw absorb the rest),
    no canonical-optional promoted to required."""
    canon = _public_verbs(plugin.HostQPNet)
    for name in sorted(canon):
        c = inspect.signature(inspect.unwrap(
            canon[name].__func__ if isinstance(canon[name], staticmethod)
            else canon[name]))
        f = inspect.signature(vars(FaultNet)[name])
        c_params = [p for p in c.parameters.values() if p.name != "self"]
        f_params = [p for p in f.parameters.values() if p.name != "self"]
        f_names = {p.name for p in f_params}
        f_varargs = any(p.kind is p.VAR_POSITIONAL for p in f_params)
        f_kwargs = any(p.kind is p.VAR_KEYWORD for p in f_params)
        c_required = [p.name for p in c_params
                      if p.default is p.empty
                      and p.kind in (p.POSITIONAL_ONLY,
                                     p.POSITIONAL_OR_KEYWORD)]
        f_required = [p.name for p in f_params
                      if p.default is p.empty
                      and p.kind in (p.POSITIONAL_ONLY,
                                     p.POSITIONAL_OR_KEYWORD)]
        assert f_required == c_required[:len(f_required)], (
            f"FaultNet.{name} required params {f_required} are not a "
            f"prefix of the canonical {c_required}")
        if len(f_required) < len(c_required):
            assert f_varargs or f_kwargs, (
                f"FaultNet.{name} drops canonical required params "
                f"{c_required[len(f_required):]} without *args/**kw")
        for p in c_params:
            if p.default is p.empty or p.name in f_names:
                continue
            assert f_kwargs or f_varargs, (
                f"FaultNet.{name} does not accept canonical optional "
                f"param {p.name!r} (add it or **kw)")


def test_one_sided_verbs_obey_the_fault_model():
    """The PR 3 wrap is behavioral, not just structural: a partitioned
    schedule blackholes iwrite (completes locally, lands nowhere) and
    never completes iread; a dead schedule refuses both, named."""
    class _StubNet:
        def iwrite(self, comm, rkey, mr, **kw):
            raise AssertionError("partitioned iwrite must not reach inner")

        def iread(self, comm, rkey, nbytes, **kw):
            raise AssertionError("partitioned iread must not reach inner")

    net = FaultNet(_StubNet(), FaultSchedule(seed=7, rank=0,
                                             partition_after_ops=0))
    req = net.iwrite("comm", 1, memoryview(b"abcd"))
    done, size = req.test()
    assert done and size == 4          # local completion, no delivery
    req = net.iread("comm", 1, 4)
    assert req.test() == (False, 0)    # never completes: caller times out

    dead = FaultNet(_StubNet(), FaultSchedule(seed=7, rank=0,
                                              die_after_ops=0))
    for verb in (lambda: dead.iwrite("c", 1, memoryview(b"x")),
                 lambda: dead.iread("c", 1, 1)):
        try:
            verb()
        except OSError as e:
            assert "comm dead" in str(e)
        else:
            raise AssertionError("dead comm must refuse one-sided verbs")


def test_one_sided_faults_are_recorded_for_replay():
    sched = FaultSchedule(seed=3, rank=1, partition_after_ops=0)
    net = FaultNet(object(), sched)
    net.iwrite("c", 1, memoryview(b"zz"))
    kinds = [k for _, k, _ in sched.log]
    assert "partitioned" in kinds
    assert sched.counters.counts["partitioned"] == 1
