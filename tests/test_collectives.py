"""Single-process multi-device tier (SURVEY.md §4): every collective on the
8-fake-CPU-device oracle, compared against numpy — the gloo-loopback analogue."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from rocnrdma_tpu import collectives as C
from rocnrdma_tpu import runtime as rt

RANK = rt.mesh.RANK_AXIS


def run_on_ring(fn, n, x, in_leading_rank=True):
    """Run an axis-level collective over an n-rank mesh on global input x
    whose leading dim is the rank axis."""
    mesh = rt.rank_mesh(n)
    spec = P(RANK) if in_leading_rank else P()
    shmapped = jax.shard_map(
        fn, mesh=mesh, in_specs=(spec,), out_specs=spec)
    return jax.jit(shmapped)(x)


def _rand(n, per, dtype=np.float32, seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n, per)).astype(dtype)


@pytest.mark.parametrize("n", [2, 3, 8])
@pytest.mark.parametrize("algo", ["ring", "ring_bidir", "fused"])
def test_allreduce_matches_numpy(devices, n, algo):
    x = _rand(n, 103)  # deliberately not divisible by n: exercises padding
    fn = {
        "ring": functools.partial(C.ring_allreduce, axis_name=RANK),
        "ring_bidir": functools.partial(C.ring_allreduce, axis_name=RANK, bidir=True),
        "fused": functools.partial(C.fused_allreduce, axis_name=RANK),
    }[algo]
    # each rank holds one row; wrap so shard shape (1, per) -> collective on row
    out = run_on_ring(lambda s: fn(s[0])[None], n, x)
    want = np.broadcast_to(x.sum(axis=0), x.shape)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5)


@pytest.mark.parametrize("n", [2, 4, 8])
def test_hd_allreduce_matches_numpy(devices, n):
    x = _rand(n, 57, seed=1)
    out = run_on_ring(lambda s: C.hd_allreduce(s[0], RANK)[None], n, x)
    want = np.broadcast_to(x.sum(axis=0), x.shape)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5)


def test_hd_allreduce_rejects_non_pow2(devices):
    x = _rand(3, 8)
    with pytest.raises(ValueError):
        run_on_ring(lambda s: C.hd_allreduce(s[0], RANK)[None], 3, x)


@pytest.mark.parametrize("n", [2, 8])
@pytest.mark.parametrize("impl", ["ring", "fused"])
def test_reduce_scatter(devices, n, impl):
    per = n * 6
    x = _rand(n, per, seed=2)
    fn = C.ring_reduce_scatter if impl == "ring" else C.fused_reduce_scatter
    out = run_on_ring(lambda s: fn(s[0], RANK)[None], n, x)
    want = x.sum(axis=0).reshape(n, -1)  # rank r owns the r-th 1/n
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5)


@pytest.mark.parametrize("n", [2, 3, 8])
@pytest.mark.parametrize("impl", ["ring", "fused"])
def test_allgather(devices, n, impl):
    x = _rand(n, 11, seed=3)
    fn = C.ring_allgather if impl == "ring" else C.fused_allgather
    # output per-rank is (n, 11); global out spec P(RANK) over leading dim
    # would shard the gathered copies — instead return replicated check value.
    mesh = rt.rank_mesh(n)
    shmapped = jax.shard_map(
        lambda s: fn(s[0], RANK)[None],
        mesh=mesh, in_specs=(P(RANK),), out_specs=P(RANK))
    out = jax.jit(shmapped)(x)  # (n, n, 11): every rank's gathered copy
    for r in range(n):
        np.testing.assert_allclose(np.asarray(out)[r], x, rtol=1e-6)


@pytest.mark.parametrize("n", [2, 3, 8])
@pytest.mark.parametrize("impl", ["rotation", "fused"])
def test_alltoall_is_transpose(devices, n, impl):
    x = _rand(n, n * 5, seed=4).reshape(n, n, 5)
    fn = C.rotation_alltoall if impl == "rotation" else C.fused_alltoall
    out = run_on_ring(lambda s: fn(s[0], RANK)[None], n, x)
    want = x.transpose(1, 0, 2)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-6)


@pytest.mark.parametrize("n", [4, 8])
@pytest.mark.parametrize("impl", ["rotation", "fused"])
def test_alltoall_involution(devices, n, impl):
    x = _rand(n, n * 3, seed=5).reshape(n, n, 3)
    fn = C.rotation_alltoall if impl == "rotation" else C.fused_alltoall
    twice = run_on_ring(lambda s: fn(fn(s[0], RANK), RANK)[None], n, x)
    np.testing.assert_allclose(np.asarray(twice), x, rtol=1e-6)


@pytest.mark.parametrize("slices,intra", [(2, 4), (4, 2)])
@pytest.mark.parametrize("cross", ["ring", "fused"])
def test_hierarchical_allreduce(devices, slices, intra, cross):
    n = slices * intra
    x = _rand(n, 37, seed=6).reshape(slices, intra, 37)
    mesh = rt.slice_mesh(slices, intra)
    fn = jax.shard_map(
        lambda s: C.hierarchical_allreduce(s[0, 0], cross_algo=cross)[None, None],
        mesh=mesh, in_specs=(P("slice", "intra"),), out_specs=P("slice", "intra"))
    out = jax.jit(fn)(x)
    want = np.broadcast_to(x.sum(axis=(0, 1)), x.shape)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("slices,intra", [(2, 4), (4, 2), (2, 2)])
@pytest.mark.parametrize("algos", [("fused", "fused"), ("rotation", "bruck")])
def test_hierarchical_alltoall_is_transpose(devices, slices, intra, algos):
    """Same transpose semantics as the flat alltoall, over the 2-level mesh
    (slice-major global rank order)."""
    N = slices * intra
    x = _rand(N, N * 3, seed=9).reshape(slices, intra, N, 3)
    mesh = rt.slice_mesh(slices, intra)
    ia, ca = algos
    fn = jax.shard_map(
        lambda s: C.hierarchical_alltoall(
            s[0, 0], intra_algo=ia, cross_algo=ca)[None, None],
        mesh=mesh, in_specs=(P("slice", "intra"),),
        out_specs=P("slice", "intra"))
    out = np.asarray(jax.jit(fn)(x)).reshape(N, N, 3)
    want = x.reshape(N, N, 3).transpose(1, 0, 2)  # the global transpose
    np.testing.assert_allclose(out, want, rtol=1e-6)


def test_hierarchical_alltoall_rejects_bad_leading(devices):
    mesh = rt.slice_mesh(2, 4)
    fn = jax.shard_map(
        lambda s: C.hierarchical_alltoall(s[0, 0])[None, None],
        mesh=mesh, in_specs=(P("slice", "intra"),),
        out_specs=P("slice", "intra"))
    with pytest.raises(ValueError, match="leading dim"):
        jax.jit(fn)(np.zeros((2, 4, 5, 3), np.float32))


@pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16])
def test_allreduce_dtypes(devices, dtype):
    # bf16 path (BASELINE.json:8). Looser tolerance for bf16 accumulate.
    n = 8
    x = _rand(n, 64).astype(dtype)
    out = run_on_ring(lambda s: C.ring_allreduce(s[0], RANK)[None], n, x)
    want = np.asarray(x, np.float32).sum(axis=0)
    # atol: ring accumulation order differs from numpy's; near-zero elements
    # show O(1) relative error at the dtype's roundoff magnitude.
    rtol, atol = (1e-5, 1e-6) if dtype == np.float32 else (5e-2, 5e-2)
    np.testing.assert_allclose(np.asarray(out, np.float32)[0], want, rtol=rtol,
                               atol=atol)


def test_allreduce_rank_permutation_invariance(devices):
    # SURVEY.md §4 property: result invariant under permuting rank buffers.
    n = 8
    x = _rand(n, 40, seed=7)
    perm = np.random.default_rng(8).permutation(n)
    f = lambda s: C.ring_allreduce(s[0], RANK)[None]
    out1 = np.asarray(run_on_ring(f, n, x))
    out2 = np.asarray(run_on_ring(f, n, x[perm]))
    np.testing.assert_allclose(out1, out2, rtol=1e-5)


@pytest.mark.parametrize("n", [2, 3, 8])
def test_bruck_alltoall_is_transpose(devices, n):
    x = _rand(n, n * 5, seed=9).reshape(n, n, 5)
    out = run_on_ring(lambda s: C.bruck_alltoall(s[0], RANK)[None], n, x)
    np.testing.assert_allclose(np.asarray(out), x.transpose(1, 0, 2), rtol=1e-6)
