"""Multi-process tier (SURVEY.md §4): real jax.distributed coordination on
one machine — 2 worker processes, 1 CPU device each — plus fault injection.

Slower than the in-process tiers (each worker pays a fresh jax import);
kept small (n=2) for suite runtime.
"""

import pytest

from rocnrdma_tpu.runtime.multiprocess import run_workers


_CPU_MP_UNSUPPORTED = "Multiprocess computations aren't implemented on the CPU backend"


def _skip_if_backend_cannot(results):
    """Old jaxlibs have no cross-process CPU collectives at all — a
    capability gap of the environment, not a regression; skip with the
    backend's own words (the host-plane chaos tier still runs, it needs
    no jax backend)."""
    if any(_CPU_MP_UNSUPPORTED in r.stderr for r in results):
        pytest.skip(f"this jaxlib: {_CPU_MP_UNSUPPORTED}")


@pytest.mark.parametrize("task", ["allreduce", "alltoall"])
def test_two_process_collective(task):
    results = run_workers(2, task, timeout_s=180)
    _skip_if_backend_cannot(results)
    for r in results:
        assert r.returncode == 0, f"rank {r.process_id} failed:\n{r.stderr[-2000:]}"
        assert f"OK rank={r.process_id}/2" in r.stdout


def test_two_process_hierarchical_dcn_path():
    """The real C13 shape: 2 processes x 2 devices, ('slice','intra') mesh
    with the slice axis ON the process boundary; the Transport's
    hierarchical allreduce and alltoall run over it."""
    results = run_workers(2, "hierarchical", timeout_s=240)
    _skip_if_backend_cannot(results)
    for r in results:
        assert r.returncode == 0, f"rank {r.rank}:\n{r.stdout}\n{r.stderr}"
        assert "hierarchical" in r.stdout


def test_fault_injection_clean_abort():
    # rank 1 dies before the init barrier; rank 0 (the coordinator) must
    # abort within its deadline — NOT hang (SURVEY.md §5). Depending on the
    # jaxlib, the abort is either a catchable RuntimeError (our wrapper exits
    # 4) or a LOG(FATAL) process termination with a diagnostic naming the
    # dead peer; both are bounded-time clean aborts. A harness kill (-9)
    # would mean a hang — the one unacceptable outcome.
    results = run_workers(2, "fault", timeout_s=180, fault_rank=1)
    assert results[1].returncode == 3, results[1]
    assert "FAULT" in results[1].stdout
    survivor = results[0]
    assert survivor.returncode not in (0, -9), \
        f"survivor: rc={survivor.returncode}\n{survivor.stderr[-2000:]}"
    blob = survivor.stdout + survivor.stderr
    assert ("CLEAN-ABORT" in blob or "DEADLINE_EXCEEDED" in blob
            or "another task died" in blob), blob[-2000:]
