"""The driver hooks (__graft_entry__) — covered in-suite so a refactor
cannot silently break what only the driver would otherwise notice."""

import numpy as np


def test_entry_compiles_and_runs(devices):
    import jax

    import __graft_entry__ as g

    fn, args = g.entry()
    assert jax.jit(fn).lower(*args).compile() is not None
    moe_out, new_params = fn(*args)
    tokens, _, params, grads, lr = args
    assert np.asarray(moe_out).shape == np.asarray(tokens).shape[:1] + (32, 64)
    # the DDP leg: params - lr * mean(grads) (1 rank => grads[0])
    for p, gr, pn in zip(params, grads, new_params):
        np.testing.assert_allclose(np.asarray(pn), p - lr * gr[0],
                                   rtol=1e-5, atol=1e-6)


def test_dryrun_multichip(devices):
    import __graft_entry__ as g

    # asserts internally (numpy oracles for dp allreduce, ep alltoall, the
    # full top-k MoE layer, grouped launch and dtree)
    g.dryrun_multichip(8)
