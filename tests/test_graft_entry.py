"""The driver hooks (__graft_entry__) — covered in-suite so a refactor
cannot silently break what only the driver would otherwise notice."""

import os

import numpy as np
import pytest


def test_entry_compiles_and_runs(devices):
    import jax

    import __graft_entry__ as g

    fn, args = g.entry()
    assert jax.jit(fn).lower(*args).compile() is not None
    moe_out, new_params = fn(*args)
    tokens, _, params, grads, lr = args
    assert np.asarray(moe_out).shape == np.asarray(tokens).shape[:1] + (32, 64)
    # the DDP leg: params - lr * mean(grads) (1 rank => grads[0])
    for p, gr, pn in zip(params, grads, new_params):
        np.testing.assert_allclose(np.asarray(pn), p - lr * gr[0],
                                   rtol=1e-5, atol=1e-6)


def test_dryrun_multichip(devices):
    import __graft_entry__ as g

    # asserts internally (numpy oracles for dp allreduce, ep alltoall, the
    # full top-k MoE layer, grouped launch and dtree)
    g.dryrun_multichip(8)


def _dryrun_in_subprocess(n, timeout=420):
    # conftest pinned THIS process to 8 fake devices; contract-scale rank
    # counts need a fresh interpreter where dryrun_multichip can still set
    # jax_num_cpu_devices itself (PYTHONPATH is exported by conftest)
    import subprocess
    import sys

    res = subprocess.run(
        [sys.executable, "-c",
         f"import __graft_entry__ as g; g.dryrun_multichip({n})"],
        capture_output=True, text=True, timeout=timeout)
    assert res.returncode == 0, res.stderr[-2000:]
    return res.stdout


def test_dryrun_multichip_16(devices):
    # VERDICT r1 item 4: the oracle must exercise >8 ranks
    out = _dryrun_in_subprocess(16)
    assert "(2, 8)" in out and "hierarchical=True" in out


def test_dryrun_multichip_nonpow2_3x5(devices):
    # 15 devices: odd composite -> a 3x5 ('slice','intra') mesh; catches
    # power-of-two assumptions anywhere in the sharded step
    out = _dryrun_in_subprocess(15)
    assert "(3, 5)" in out and "hierarchical=True" in out


def test_mesh_factor():
    import __graft_entry__ as g

    assert g._mesh_factor(16) == (2, 8)
    assert g._mesh_factor(15) == (3, 5)
    assert g._mesh_factor(9) == (3, 3)
    for prime_or_small in (1, 2, 3, 7, 13):
        assert g._mesh_factor(prime_or_small) is None


def test_dryrun_multichip_contract_64(devices):
    # the BASELINE.json:9 rank count, end to end (measured ~13 s cold)
    out = _dryrun_in_subprocess(64)
    assert "(2, 32)" in out and "hierarchical=True" in out


@pytest.mark.skipif(os.environ.get("RNR_SKIP_SLOW", "") not in ("", "0"),
                    reason="RNR_SKIP_SLOW set")
def test_dryrun_multichip_contract_128(devices):
    # VERDICT r3 next #9: exercise the contract-scale rank-count axis
    # (BASELINE.json:5, v5p-256) before first contact. 128 fake devices
    # timeshare the CPU core for ~7 min — the suite's slowest single test
    # (256 measured >15 min, past any sane CI budget; the sharding logic
    # it would add beyond 128 is the same code paths at 2x fan-out).
    # Skippable via RNR_SKIP_SLOW=1 for quick local loops.
    out = _dryrun_in_subprocess(128, timeout=900)
    assert "(2, 64)" in out and "hierarchical=True" in out


@pytest.mark.skipif(os.environ.get("RNR_SKIP_SLOW", "") not in ("", "0"),
                    reason="RNR_SKIP_SLOW set")
def test_dryrun_multichip_contract_256_light(devices):
    # VERDICT r4 missing #6 / next #7: the contract rank count itself
    # (v5p-256, BASELINE.json:5) — payload-shrunk light mode (the full
    # surface measured >15 min at this fan-out; light keeps the contract-
    # critical multi-chip surfaces and ran in ~80 s, committed at
    # results/dryrun256_light.log). Mesh (2, 128) IS the contract's
    # 2xv5p-128 shape.
    out = _dryrun_in_subprocess(256, timeout=600)
    assert "(2, 128)" in out and "hierarchical=True" in out
    assert "LIGHT" in out
