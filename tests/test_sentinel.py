"""The perf regression sentinel (ISSUE 11, lite): record-vs-record
diffing, the trace-attribution self-diagnosis, the coalesce speedup
ratchet, and the committed results/ artifacts diffing clean against
themselves (the all-zero ratchet property, like tools/analyze's)."""

import copy
import json
import os
import subprocess
import sys

import pytest

from tools import sentinel

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _row(algo="coalesced", platform="host-shm", algbw=0.5, trace=None,
         coalesce=None):
    extra = {}
    if trace is not None:
        extra["trace"] = trace
    if coalesce is not None:
        extra["coalesce"] = coalesce
    return {"bench": "bench_host", "collective": "allreduce",
            "algo": algo, "n_ranks": 2, "size_bytes": 65536,
            "dtype": "float32", "mean_s": 1e-4, "algbw_GBps": algbw,
            "busbw_GBps": algbw, "platform": platform, "extra": extra}


def test_committed_records_self_diff_is_clean():
    """The ratchet's fixed point: the committed records can never be a
    regression against themselves."""
    committed = sentinel.committed_records()
    assert committed, "results/coalesce_r01.json should carry records"
    assert sentinel.check_current(committed) == []


def test_committed_coalesce_record_schema():
    with open(os.path.join(REPO, "results", "coalesce_r01.json")) as fp:
        doc = json.load(fp)
    assert doc["schema"] == "coalesce_r01"
    assert doc["scenario"]["ops"] == 256
    assert doc["scenario"]["small_bytes"] == 65536
    assert doc["floors"]["speedup_min"] == 2.0
    # the acceptance multiple held on BOTH planes when recorded
    assert doc["floors"]["shm_speedup"] >= 2.0
    assert doc["floors"]["tcp_speedup"] >= 2.0
    planes = {r["platform"] for r in doc["records"]}
    assert planes == {"host-shm", "host-tcp"}
    for r in doc["records"]:
        if r["algo"] != "coalesced":
            continue
        assert r["extra"]["coalesce"]["bitwise_ok"] is True
        assert r["extra"]["coalesce"]["speedup"] >= 2.0


def test_compare_flags_regressed_row_with_attribution_diff():
    base = [_row(algbw=1.0, trace={"cp_rank": 0, "attribution_us":
                                   {"wire": 100.0, "recv-wait": 50.0}})]
    cur = [_row(algbw=0.5, trace={"cp_rank": 1, "attribution_us":
                                  {"wire": 400.0, "recv-wait": 60.0}})]
    findings = sentinel.compare(cur, base)
    assert len(findings) == 1
    f = findings[0]
    assert f["committed_GBps"] == 1.0 and f["algbw_GBps"] == 0.5
    # the self-diagnosis: WHICH bucket grew
    assert f["trace_diff"]["grew"] == "wire"
    assert f["trace_diff"]["grew_us"] == pytest.approx(300.0)
    text = sentinel.format_findings(findings)
    assert "wire grew" in text and "regression" in text


def test_compare_within_noise_allowance_is_clean():
    base = [_row(algbw=1.0)]
    assert sentinel.compare([_row(algbw=0.85)], base) == []
    assert sentinel.compare([_row(algbw=0.79)], base) != []


def test_compare_ignores_rows_with_no_committed_twin():
    cur = [_row(algo="brand-new-scenario", algbw=0.001)]
    assert sentinel.compare(cur, [_row(algbw=1.0)]) == []


def test_attribution_diff_with_no_grown_bucket_says_so():
    # the row regressed but the sampled op was FASTER everywhere: the
    # diff must not blame a bucket that shrank
    findings = sentinel.compare(
        [_row(algbw=0.1, trace={"attribution_us": {"wire": 10.0,
                                                   "recv-wait": 5.0}})],
        [_row(algbw=1.0, trace={"attribution_us": {"wire": 100.0,
                                                   "recv-wait": 50.0}})])
    td = findings[0]["trace_diff"]
    assert td["grew"] is None
    assert "no bucket grew" in sentinel.format_findings(findings)


def test_attribution_diff_refuses_to_invent_blame():
    # either side missing its sampled trace -> no diff, never a guess
    assert sentinel.attribution_diff(None, {"attribution_us": {}}) is None
    assert sentinel.attribution_diff({"attribution_us": {"wire": 1.0}},
                                     {}) is None
    findings = sentinel.compare(
        [_row(algbw=0.1)], [_row(algbw=1.0)])
    assert findings[0]["trace_diff"] is None
    assert "no sampled trace" in sentinel.format_findings(findings)


def test_speedup_floor_ratchet():
    good = [_row(coalesce={"speedup": 5.0, "bitwise_ok": True})]
    bad = [_row(coalesce={"speedup": 1.5, "bitwise_ok": True})]
    assert sentinel.check_speedup_floor(good) == []
    findings = sentinel.check_speedup_floor(bad)
    assert len(findings) == 1 and findings[0]["floor"] == 2.0
    assert "fell below" in sentinel.format_findings(findings)


def test_missing_results_dir_is_not_a_regression(tmp_path):
    # a fresh clone mid-history (records not yet committed) must not
    # fail the ratchet for artifacts that do not exist
    assert sentinel.committed_records(str(tmp_path)) == []
    assert sentinel.check_current([_row()], results_dir=str(tmp_path)) == []


def test_cli_end_to_end(tmp_path):
    current = tmp_path / "cur.jsonl"
    committed = sentinel.committed_records()
    with open(current, "w") as fp:
        for rec in committed:
            fp.write(json.dumps(rec) + "\n")
    out = subprocess.run(
        [sys.executable, "-m", "tools.sentinel", "--records", str(current)],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "no perf regressions" in out.stdout
    # degrade one row: exit 1 + the named finding
    rows = [copy.deepcopy(r) for r in committed]
    rows[0]["algbw_GBps"] *= 0.3
    with open(current, "w") as fp:
        for rec in rows:
            fp.write(json.dumps(rec) + "\n")
    out = subprocess.run(
        [sys.executable, "-m", "tools.sentinel", "--records", str(current)],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "regression" in out.stdout


def test_cli_refuses_ambiguous_inputs():
    out = subprocess.run(
        [sys.executable, "-m", "tools.sentinel"],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert out.returncode == 2
    assert "exactly one of" in out.stderr


# ------------------------------------------- statistical half (ISSUE 12)
# Spread-resolved regression (non-overlapping trial intervals), the
# wp99-creep and cp-share-drift decay checks, and the committed tune
# artifact diffing clean against itself.


def _spread_row(algbw=0.5, spread=None, fleet=None, trace=None,
                algo="ring", platform="host-shm"):
    r = _row(algo=algo, platform=platform, algbw=algbw, trace=trace)
    if spread is not None:
        r["extra"]["spread"] = spread
    if fleet is not None:
        r["extra"]["fleet"] = fleet
    return r


def test_compare_overlapping_spread_is_noise_not_regression():
    # a 25% slide whose trial intervals still overlap: trial noise —
    # the fixed 0.8x ratio would have flagged it, the statistics don't
    base = _spread_row(algbw=1.0, spread=[0.7, 1.3])
    cur = _spread_row(algbw=0.75, spread=[0.72, 0.8])
    assert sentinel.compare([cur], [base]) == []


def test_compare_non_overlapping_spread_flags_inside_ratio():
    # a tight 12% slide the 0.8x ratio would PASS, but the intervals
    # do not overlap: statistically resolved regression
    base = _spread_row(algbw=1.0, spread=[0.98, 1.02])
    cur = _spread_row(algbw=0.88, spread=[0.86, 0.9])
    [f] = sentinel.compare([cur], [base])
    assert f["stat"] == "non-overlapping-spread"
    assert f["floor_GBps"] == 0.98
    assert "non-overlapping" in sentinel.format_findings([f])


def test_compare_without_spread_keeps_ratio_floor():
    base = _spread_row(algbw=1.0)
    cur = _spread_row(algbw=0.75)
    [f] = sentinel.compare([cur], [base])
    assert f["stat"].startswith("ratio-")
    assert sentinel.compare([_spread_row(algbw=0.85)], [base]) == []


def test_wp99_creep_flags_tail_decay_headline_green():
    base = _spread_row(algbw=1.0, fleet={"worst_p99_us": 4096})
    ok = _spread_row(algbw=1.0,
                     fleet={"worst_p99_us": 8192})     # 2x: inside
    bad = _spread_row(algbw=1.0,
                      fleet={"worst_p99_us": 32768})   # 8x: creep
    assert sentinel.check_wp99_creep([ok], [base]) == []
    [f] = sentinel.check_wp99_creep([bad], [base])
    assert f["factor"] == 8.0
    assert "crept" in sentinel.format_findings([f])
    # missing fleet telemetry on either side: skipped, never invented
    assert sentinel.check_wp99_creep([_spread_row(algbw=1.0)],
                                     [base]) == []


def test_cp_share_drift_flags_forming_straggler():
    base = _spread_row(algbw=1.0, trace={
        "cp_share": {"0": 50.0, "1": 50.0}})
    ok = _spread_row(algbw=1.0, trace={
        "cp_share": {"0": 60.0, "1": 40.0}})   # 0.6 vs 0.5: inside
    bad = _spread_row(algbw=1.0, trace={
        "cp_share": {"0": 90.0, "1": 10.0}})   # 0.9: drifted 0.4
    assert sentinel.check_cp_share_drift([ok], [base]) == []
    [f] = sentinel.check_cp_share_drift([bad], [base])
    assert f["cp_max_share"] == 0.9
    assert "straggler" in sentinel.format_findings([f])
    assert sentinel.check_cp_share_drift(
        [_spread_row(algbw=1.0)], [base]) == []


def test_committed_tune_artifact_self_diff_is_clean():
    # the tune_r01 rows are committed floor material like the others:
    # their own records must diff clean against themselves (including
    # through the creep/drift checks — the all-zero ratchet property)
    path = os.path.join(REPO, "results", "tune_r01.json")
    if not os.path.exists(path):
        pytest.skip("tune_r01.json not recorded yet")
    with open(path) as fp:
        rows = json.load(fp).get("records", [])
    assert rows, "tune_r01.json carries no records"
    for r in rows:
        assert sentinel._spread(r) is not None, \
            "tune rows must carry the statistical spread field"
    assert sentinel.check_current(rows) == []
