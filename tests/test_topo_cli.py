"""Topology dump CLI (RCCL topo-dump analogue) and Transport telemetry."""

import json

import numpy as np

from rocnrdma_tpu import runtime as rt
from rocnrdma_tpu.runtime import topo_cli
from rocnrdma_tpu.transport import Transport


class _FakeDev:
    def __init__(self, i, coords):
        self.id = i
        self.coords = coords
        self.device_kind = "fake tpu"
        self.process_index = 0
        self.core_on_chip = 0
        self.platform = "tpu"
        self.client = None


def test_describe_oracle(devices):
    doc = topo_cli.describe()
    assert doc["platform"] == "cpu" and doc["is_oracle"]
    assert doc["n_devices"] == 8
    assert doc["ring_order"] == [d["id"] for d in doc["devices"]]
    assert "ring_hop_lengths" not in doc  # no coords on fakes
    out = topo_cli.render(doc)
    assert "CPU oracle" in out and "snake ring order" in out


def test_describe_with_coords_reports_contiguity(devices):
    # a 2x4 grid: snake order must make every hop one physical step
    fakes = [_FakeDev(i, (x, y)) for i, (x, y) in enumerate(
        [(x, y) for x in range(2) for y in range(4)])]
    doc = topo_cli.describe(fakes)
    assert doc["grid_dims"] == [2, 4]
    assert doc["ring_contiguous"] is True
    assert all(h == 1 for h in doc["ring_hop_lengths"][:-1])
    assert "hop lengths" in topo_cli.render(doc)


def test_cli_json(devices, capsys):
    assert topo_cli.main(["--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["n_devices"] == 8


def test_transport_stats_count_calls_and_bytes(devices):
    t = Transport(rt.rank_mesh(4))
    x = t.shard(np.zeros((4, 256), np.float32))
    t.allreduce(x)
    t.allreduce(x)
    t.allgather(x, algo="ring")
    with t.group() as g:
        g.alltoall(t.shard(np.zeros((4, 4, 2), np.float32)))
    s = t.stats()
    assert s["allreduce/fused"]["calls"] == 2
    assert s["allreduce/fused"]["bytes"] == 2 * x.nbytes
    assert s["allgather/ring"]["calls"] == 1
    assert s["alltoall/fused"]["calls"] == 1
    table = t.format_stats()
    assert "allreduce/fused" in table and "calls" in table


def test_rnr_debug_logs_dispatches(devices, capsys, monkeypatch):
    """RNR_DEBUG=1 (the NCCL_DEBUG=INFO analogue) logs one line per call."""
    from rocnrdma_tpu.transport import api

    monkeypatch.setattr(api, "_DEBUG_LOG", True)
    t = Transport(rt.rank_mesh(4))
    x = t.shard(np.zeros((4, 32), np.float32))
    t.allreduce(x, algo="ring")
    err = capsys.readouterr().err
    assert "# rnr allreduce algo=ring bytes=512 ranks=4 mesh=1d" in err
