"""One-sided (put-based) ring allreduce: the RDMA-write data path over both
host planes — doorbell flags, credits, slot recycling, state reuse."""

import threading

import numpy as np
import pytest

from rocnrdma_tpu import native
from rocnrdma_tpu.transport import HostQPNet, TCPNet
from rocnrdma_tpu.transport.plugin import (
    ring_allgather_rdma,
    ring_allreduce_over_net,
    ring_allreduce_rdma,
    ring_reduce_scatter_rdma,
)

needs_native = pytest.mark.skipif(
    not native.available(), reason="native library not buildable")

PLANES = [HostQPNet, TCPNet]


def _run_ring(net_cls, n, fn):
    net = net_cls()
    net.init()
    handles, listens = [], []
    for _ in range(n):
        h, l = net.listen()
        handles.append(h)
        listens.append(l)
    results: list = [None] * n
    errors: list = []

    def worker(rank):
        try:
            send_comm = net.connect(0, handles[(rank + 1) % n])
            recv_comm = net.accept(listens[rank])
            results[rank] = fn(net, send_comm, recv_comm, rank)
        except Exception as e:
            errors.append((rank, repr(e)))

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    assert not errors, errors
    net.close()
    return results


@needs_native
@pytest.mark.parametrize("net_cls", PLANES)
@pytest.mark.parametrize("n", [2, 3, 4])
def test_rdma_ring_matches_numpy(net_cls, n):
    rng = np.random.default_rng(1)
    xs = [rng.standard_normal(509).astype(np.float32)  # odd: uneven chunks
          for _ in range(n)]
    res = _run_ring(net_cls, n, lambda net, s, r, rank:
                    ring_allreduce_rdma(net, s, r, xs[rank], rank, n))
    want = np.sum(xs, axis=0)
    for r in range(n):
        np.testing.assert_allclose(res[r], want, rtol=1e-5, atol=1e-5)


@needs_native
@pytest.mark.parametrize("net_cls", PLANES)
def test_rdma_ring_repeated_calls_reuse_state(net_cls):
    """Back-to-back calls recycle the cached MRs (hop counter monotonic)."""
    n = 2
    rng = np.random.default_rng(2)
    xs = [rng.standard_normal(1000).astype(np.float32) for _ in range(n)]

    def fn(net, s, r, rank):
        outs = [ring_allreduce_rdma(net, s, r, xs[rank] * (i + 1), rank, n)
                for i in range(4)]
        assert r._rdma_ring["hop"] == 4 * 2 * (n - 1)
        return outs

    res = _run_ring(net_cls, n, fn)
    want = np.sum(xs, axis=0)
    for r in range(n):
        for i in range(4):
            np.testing.assert_allclose(res[r][i], want * (i + 1),
                                       rtol=1e-5, atol=1e-4)


@needs_native
@pytest.mark.parametrize("op,npf", [("max", np.max), ("prod", np.prod)])
def test_rdma_ring_ops(op, npf):
    n = 3
    rng = np.random.default_rng(3)
    xs = [(rng.standard_normal(64) + 2.0).astype(np.float32)
          for _ in range(n)]
    res = _run_ring(TCPNet, n, lambda net, s, r, rank:
                    ring_allreduce_rdma(net, s, r, xs[rank], rank, n, op=op))
    want = npf(xs, axis=0)
    for r in range(n):
        np.testing.assert_allclose(res[r], want, rtol=1e-4)


@needs_native
def test_rdma_ring_matches_msg_ring():
    """Both transports compute identical results (same schedule order)."""
    n = 4
    rng = np.random.default_rng(4)
    xs = [rng.standard_normal(256).astype(np.float32) for _ in range(n)]

    def fn(net, s, r, rank):
        a = ring_allreduce_rdma(net, s, r, xs[rank], rank, n)
        b = ring_allreduce_over_net(net, s, r, xs[rank], rank, n)
        return a, b

    res = _run_ring(HostQPNet, n, fn)
    for r in range(n):
        np.testing.assert_array_equal(res[r][0], res[r][1])


@needs_native
def test_rdma_ring_large_hop_flushes_at_exit():
    """Regression: the final put must flush before return — a fast rank
    exiting with its last hop queued in user space starves the peer
    (observed at 16 MB hops over TCP)."""
    n = 2
    rng = np.random.default_rng(6)
    xs = [rng.standard_normal(2 * 1024 * 1024).astype(np.float32)  # 8 MB
          for _ in range(n)]
    res = _run_ring(TCPNet, n, lambda net, s, r, rank:
                    ring_allreduce_rdma(net, s, r, xs[rank], rank, n))
    want = np.sum(xs, axis=0)
    for r in range(n):
        np.testing.assert_allclose(res[r], want, rtol=1e-5, atol=1e-5)


@needs_native
def test_rdma_ring_grows_capacity():
    """A bigger buffer on reused comms re-registers larger MRs."""
    n = 2
    rng = np.random.default_rng(5)
    small = [rng.standard_normal(64).astype(np.float32) for _ in range(n)]
    big = [rng.standard_normal(4096).astype(np.float32) for _ in range(n)]

    def fn(net, s, r, rank):
        a = ring_allreduce_rdma(net, s, r, small[rank], rank, n)
        cap1 = r._rdma_ring["cap"]
        b = ring_allreduce_rdma(net, s, r, big[rank], rank, n)
        assert r._rdma_ring["cap"] > cap1
        return a, b

    res = _run_ring(TCPNet, n, fn)
    for r in range(n):
        np.testing.assert_allclose(res[r][0], np.sum(small, axis=0), rtol=1e-5)
        np.testing.assert_allclose(res[r][1], np.sum(big, axis=0),
                                   rtol=1e-5, atol=1e-5)


@needs_native
@pytest.mark.parametrize("net_cls", PLANES)
@pytest.mark.parametrize("n", [2, 3, 4])
def test_rdma_reduce_scatter(net_cls, n):
    rng = np.random.default_rng(7)
    # 509 is odd: ragged floor-balanced chunks, unequal per-hop byte counts
    xs = [rng.standard_normal(509).astype(np.float32) for _ in range(n)]
    res = _run_ring(net_cls, n, lambda net, s, r, rank:
                    ring_reduce_scatter_rdma(net, s, r, xs[rank], rank, n))
    total = np.sum(xs, axis=0)
    bounds = [len(total) * i // n for i in range(n + 1)]
    for r in range(n):
        np.testing.assert_allclose(res[r], total[bounds[r]:bounds[r + 1]],
                                   rtol=1e-5, atol=1e-5)


@needs_native
@pytest.mark.parametrize("net_cls", PLANES)
@pytest.mark.parametrize("n", [2, 3, 4])
def test_rdma_allgather(net_cls, n):
    rng = np.random.default_rng(8)
    blocks = [rng.standard_normal(257).astype(np.float32) for _ in range(n)]
    res = _run_ring(net_cls, n, lambda net, s, r, rank:
                    ring_allgather_rdma(net, s, r, blocks[rank], rank, n))
    want = np.stack(blocks)
    for r in range(n):
        np.testing.assert_array_equal(res[r], want)


@needs_native
def test_rdma_family_shares_connection_state():
    """Back-to-back rdma collectives on the same comms share the doorbell
    hop counter and MR state — the sequence must stay correct."""
    n = 2
    rng = np.random.default_rng(9)
    xs = [rng.standard_normal(300).astype(np.float32) for _ in range(n)]

    def fn(net, s, r, rank):
        a = ring_allreduce_rdma(net, s, r, xs[rank], rank, n)
        b = ring_reduce_scatter_rdma(net, s, r, xs[rank], rank, n)
        c = ring_allgather_rdma(net, s, r, xs[rank], rank, n)
        return a, b, c

    res = _run_ring(TCPNet, n, fn)
    total = np.sum(xs, axis=0)
    bounds = [300 * i // n for i in range(n + 1)]
    for r in range(n):
        a, b, c = res[r]
        np.testing.assert_allclose(a, total, rtol=1e-5)
        np.testing.assert_allclose(b, total[bounds[r]:bounds[r + 1]],
                                   rtol=1e-5)
        np.testing.assert_array_equal(c, np.stack(xs))


@needs_native
@pytest.mark.parametrize("net_cls", PLANES)
def test_rdma_soak_random_mixed_sequence(net_cls):
    """Soak the put/take engine: a random mixed collective sequence with
    jumping sizes on ONE connection pair per rank — MR growth and shrink
    reuse, slot parity, hop-counter continuity across collectives, and
    the deferred-ack consume window (the zero-copy refactor's riskiest
    paths) all exercised in one run."""
    n = 3
    seq = np.random.default_rng(77)
    ops = seq.choice(["ar", "rs", "ag"], size=18)
    sizes = seq.integers(1, 5000, size=18)
    datas = [np.random.default_rng(100 + i)
             .standard_normal((n, int(s))).astype(np.float32)
             for i, s in enumerate(sizes)]

    def fn(net, s, r, rank):
        out = []
        for i, op in enumerate(ops):
            x = datas[i][rank]
            if op == "ar":
                out.append(ring_allreduce_rdma(net, s, r, x, rank, n))
            elif op == "rs":
                out.append(ring_reduce_scatter_rdma(net, s, r, x, rank, n))
            else:
                out.append(ring_allgather_rdma(net, s, r, x, rank, n))
        return out

    res = _run_ring(net_cls, n, fn)
    for i, op in enumerate(ops):
        total = datas[i].sum(axis=0)
        m = len(total)
        bounds = [m * j // n for j in range(n + 1)]
        for r in range(n):
            got = res[r][i]
            if op == "ar":
                np.testing.assert_allclose(got, total, rtol=1e-5,
                                           atol=1e-5, err_msg=f"op {i}")
            elif op == "rs":
                np.testing.assert_allclose(
                    got, total[bounds[r]:bounds[r + 1]], rtol=1e-5,
                    atol=1e-5, err_msg=f"op {i}")
            else:
                np.testing.assert_array_equal(got, datas[i],
                                              err_msg=f"op {i}")
