"""Physical ring ordering (runtime/topology.py): every consecutive pair of
ranks must sit one ICI hop apart on the torus."""

import dataclasses
import random

import pytest

from rocnrdma_tpu.runtime.topology import (
    grid_dims, ring_hop_lengths, ring_order, snake_rank, torus_distance)


@dataclasses.dataclass(frozen=True)
class FakeDev:
    id: int
    coords: tuple
    core_on_chip: int = 0


def _grid(*dims):
    devs = []
    i = 0
    if len(dims) == 2:
        for x in range(dims[0]):
            for y in range(dims[1]):
                devs.append(FakeDev(i, (x, y)))
                i += 1
    else:
        for x in range(dims[0]):
            for y in range(dims[1]):
                for z in range(dims[2]):
                    devs.append(FakeDev(i, (x, y, z)))
                    i += 1
    return devs


def test_snake_rank_bijective_2d():
    dims = (4, 4)
    ranks = {snake_rank((x, y), dims) for x in range(4) for y in range(4)}
    assert ranks == set(range(16))


@pytest.mark.parametrize("dims", [(2, 2), (4, 4), (4, 8), (2, 2, 2), (4, 4, 4)])
def test_snake_consecutive_are_neighbors(dims):
    devs = _grid(*dims)
    random.Random(0).shuffle(devs)
    ordered = ring_order(devs)
    assert len(ordered) == len(devs)
    for a, b in zip(ordered, ordered[1:]):
        assert torus_distance(a.coords, b.coords, dims) == 1, (a, b)


def test_closing_hop_rides_wraparound():
    # on a wrapped torus the last->first hop is also one link when every
    # snake-reversed axis has even extent (true of real TPU tori: 4x4, 4x8..)
    devs = _grid(4, 4)
    ordered = ring_order(devs)
    hops = ring_hop_lengths(ordered)
    assert hops == [1] * len(hops)


def test_cores_on_one_chip_stay_adjacent():
    devs = []
    i = 0
    for x in range(2):
        for y in range(2):
            for core in range(2):
                devs.append(FakeDev(i, (x, y), core))
                i += 1
    random.Random(1).shuffle(devs)
    ordered = ring_order(devs)
    # pairs of same-chip cores must be consecutive, core 0 first
    for j in range(0, len(ordered), 2):
        assert ordered[j].coords == ordered[j + 1].coords
        assert (ordered[j].core_on_chip, ordered[j + 1].core_on_chip) == (0, 1)
    hops = ring_hop_lengths(ordered)
    assert max(hops) == 1 and hops.count(0) == 4  # on-chip "hops" are free


def test_no_coords_falls_back_to_given_order():
    class Bare:
        def __init__(self, i):
            self.id = i
    devs = [Bare(i) for i in range(8)]
    assert ring_order(devs) == devs


def test_snake_beats_naive_order_on_average_hop():
    # the whole point: id order (row-major) pays a long hop at every row seam
    dims = (4, 4)
    devs = _grid(*dims)
    naive = sum(torus_distance(a.coords, b.coords, dims)
                for a, b in zip(devs, devs[1:]))
    ordered = ring_order(devs)
    snake = sum(torus_distance(a.coords, b.coords, dims)
                for a, b in zip(ordered, ordered[1:]))
    assert snake < naive
    assert snake == len(devs) - 1  # every hop is exactly one link


def test_grid_dims_subgrid():
    devs = [FakeDev(0, (0, 0)), FakeDev(1, (0, 1)), FakeDev(2, (1, 0)),
            FakeDev(3, (1, 1)), FakeDev(4, (2, 0)), FakeDev(5, (2, 1))]
    assert grid_dims([d.coords for d in devs]) == [3, 2]
    ordered = ring_order(devs)
    for a, b in zip(ordered, ordered[1:]):
        assert torus_distance(a.coords, b.coords, (3, 2)) == 1


def test_mesh_builders_still_work_on_oracle(devices):
    # CPU fakes have no coords: rank_mesh/slice_mesh keep their old behavior
    from rocnrdma_tpu import runtime as rt
    m1 = rt.rank_mesh(8)
    assert m1.devices.shape == (8,)
    m2 = rt.slice_mesh(2, 4)
    assert m2.devices.shape == (2, 4)
