"""The host-plane collective family riding the net-plugin verbs, over BOTH
wires (shm queue pairs and TCP queue pairs) — the gloo-analogue surface."""

import threading

import numpy as np
import pytest

from rocnrdma_tpu import native
from rocnrdma_tpu.transport import (
    HostQPNet,
    TCPNet,
    ring_allgather_over_net,
    ring_allgatherv_over_net,
    ring_allreduce_over_net,
    ring_alltoall_over_net,
    ring_broadcast_over_net,
    ring_reduce_scatter_over_net,
    ring_reduce_scatter_v_over_net,
)

needs_native = pytest.mark.skipif(
    not native.available(), reason="native library not buildable")


def _run_ring(net_cls, n, fn):
    """Wire an n-rank ring over one net; run fn(net, send, recv, rank) per
    rank in threads; return per-rank results."""
    net = net_cls()
    net.init()
    handles, listens = [], []
    for _ in range(n):
        h, l = net.listen()
        handles.append(h)
        listens.append(l)
    results: list = [None] * n
    errors: list = []

    def worker(rank):
        try:
            send_comm = net.connect(0, handles[(rank + 1) % n])
            recv_comm = net.accept(listens[rank])
            results[rank] = fn(net, send_comm, recv_comm, rank)
        except Exception as e:
            errors.append((rank, e))

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    assert not errors, errors
    net.close()
    return results


PLANES = [HostQPNet, TCPNet]


@needs_native
@pytest.mark.parametrize("net_cls", PLANES)
@pytest.mark.parametrize("n", [2, 4])
def test_allgather_over_net(net_cls, n):
    rng = np.random.default_rng(1)
    blocks = [rng.standard_normal(257).astype(np.float32) for _ in range(n)]
    res = _run_ring(net_cls, n, lambda net, s, r, rank:
                    ring_allgather_over_net(net, s, r, blocks[rank], rank, n))
    want = np.stack(blocks)
    for r in range(n):
        np.testing.assert_array_equal(res[r], want)


@needs_native
@pytest.mark.parametrize("net_cls", PLANES)
@pytest.mark.parametrize("n", [2, 4])
def test_allgatherv_over_net(net_cls, n):
    # ragged sizes per rank (one empty — the degenerate car must ride fine)
    rng = np.random.default_rng(11)
    counts = [257, 0, 31, 1024][:n]
    segs = [rng.standard_normal(c).astype(np.float32) for c in counts]
    res = _run_ring(net_cls, n, lambda net, s, r, rank:
                    ring_allgatherv_over_net(net, s, r, segs[rank], counts,
                                             rank, n))
    for r in range(n):
        assert len(res[r]) == n
        for j in range(n):
            np.testing.assert_array_equal(res[r][j], segs[j])


@needs_native
@pytest.mark.parametrize("net_cls", PLANES)
@pytest.mark.parametrize("n,op", [(2, "sum"), (4, "sum"), (4, "max"),
                                  (3, "min")])
def test_reduce_scatter_v_over_net(net_cls, n, op):
    rng = np.random.default_rng(12)
    counts = [7, 0, 129, 33][:n]
    total = sum(counts)
    xs = [rng.standard_normal(total).astype(np.float32) for _ in range(n)]
    res = _run_ring(net_cls, n, lambda net, s, r, rank:
                    ring_reduce_scatter_v_over_net(net, s, r, xs[rank],
                                                   counts, rank, n, op=op))
    npf = {"sum": np.sum, "max": np.max, "min": np.min}[op]
    full = npf(np.stack(xs), axis=0)
    bounds = np.concatenate([[0], np.cumsum(counts)])
    for r in range(n):
        np.testing.assert_allclose(res[r], full[bounds[r]:bounds[r + 1]],
                                   rtol=1e-5, atol=1e-6)


def test_ragged_v_count_validation():
    # shape/count mismatches fail fast, before any wire traffic (n=1 path
    # exercises the same validation the multi-rank path runs)
    with pytest.raises(ValueError, match="counts"):
        ring_allgatherv_over_net(None, None, None,
                                 np.zeros(3, np.float32), [3, 3], 0, 1)
    with pytest.raises(ValueError, match="elements"):
        ring_allgatherv_over_net(None, None, None,
                                 np.zeros(3, np.float32), [4], 0, 1)
    with pytest.raises(ValueError, match="counts sum"):
        ring_reduce_scatter_v_over_net(None, None, None,
                                       np.zeros(3, np.float32), [4], 0, 1)


@needs_native
@pytest.mark.parametrize("net_cls", PLANES)
@pytest.mark.parametrize("n,root", [(2, 0), (4, 2), (3, 1)])
def test_broadcast_over_net(net_cls, n, root):
    rng = np.random.default_rng(2)
    payload = rng.standard_normal(100000).astype(np.float32)  # multi-chunk
    def fn(net, s, r, rank):
        local = payload if rank == root else np.zeros_like(payload)
        return ring_broadcast_over_net(net, s, r, local, rank, n, root=root)
    res = _run_ring(net_cls, n, fn)
    for r in range(n):
        np.testing.assert_array_equal(res[r], payload)


@needs_native
@pytest.mark.parametrize("net_cls", PLANES)
@pytest.mark.parametrize("n", [2, 3, 4])
def test_alltoall_over_net(net_cls, n):
    rng = np.random.default_rng(3)
    mats = [rng.standard_normal((n, 41)).astype(np.float32) for _ in range(n)]
    res = _run_ring(net_cls, n, lambda net, s, r, rank:
                    ring_alltoall_over_net(net, s, r, mats[rank], rank, n))
    for r in range(n):
        want = np.stack([mats[src][r] for src in range(n)])
        np.testing.assert_array_equal(res[r], want)


@needs_native
@pytest.mark.parametrize("net_cls", PLANES)
@pytest.mark.parametrize("n", [2, 4])
def test_reduce_scatter_over_net(net_cls, n):
    rng = np.random.default_rng(5)
    xs = [rng.standard_normal(n * 53).astype(np.float32) for _ in range(n)]
    res = _run_ring(net_cls, n, lambda net, s, r, rank:
                    ring_reduce_scatter_over_net(net, s, r, xs[rank], rank, n))
    total = np.sum(xs, axis=0)
    bounds = [len(total) * i // n for i in range(n + 1)]
    for r in range(n):
        # standard semantics: rank r keeps range r (composes with allgather)
        want = total[bounds[r]:bounds[r + 1]]
        np.testing.assert_allclose(res[r], want, rtol=1e-5, atol=1e-5)


@needs_native
@pytest.mark.parametrize("net_cls", PLANES)
@pytest.mark.parametrize("n,root", [(2, 1), (4, 2), (3, 0)])
def test_reduce_over_net(net_cls, n, root):
    from rocnrdma_tpu.transport.plugin import ring_reduce_over_net
    rng = np.random.default_rng(11)
    # multi-chunk on the shm plane: > MAX_FRAME bytes forces pipelining
    # (150k floats = 600 KB > the r3 512 KiB frame)
    xs = [rng.standard_normal(150000).astype(np.float32) for _ in range(n)]
    res = _run_ring(net_cls, n, lambda net, s, r, rank:
                    ring_reduce_over_net(net, s, r, xs[rank], rank, n,
                                         root=root))
    want = np.sum(xs, axis=0)
    for r in range(n):
        if r == root:
            np.testing.assert_allclose(res[r], want, rtol=1e-5, atol=1e-5)
        else:
            assert res[r] is None


@needs_native
@pytest.mark.parametrize("net_cls", PLANES)
@pytest.mark.parametrize("n,root", [(2, 0), (4, 3)])
def test_gather_scatter_over_net(net_cls, n, root):
    from rocnrdma_tpu.transport.plugin import (
        ring_gather_over_net,
        ring_scatter_over_net,
    )
    rng = np.random.default_rng(12)
    blocks = [rng.standard_normal((3, 17)).astype(np.float32)
              for _ in range(n)]
    rows = rng.standard_normal((n, 29)).astype(np.float32)

    def fn(net, s, r, rank):
        g = ring_gather_over_net(net, s, r, blocks[rank], rank, n, root=root)
        sc = ring_scatter_over_net(
            net, s, r, rows if rank == root else np.empty(29, np.float32),
            rank, n, root=root)
        return g, sc

    res = _run_ring(net_cls, n, fn)
    for r in range(n):
        g, sc = res[r]
        if r == root:
            np.testing.assert_array_equal(g, np.stack(blocks))
        else:
            assert g is None
        np.testing.assert_array_equal(sc, rows[r])


@needs_native
def test_large_hop_exceeding_kernel_buffers():
    """Regression: a hop bigger than the kernel socket buffers must not
    deadlock (each side's tail frames sit in the user-space tx queue; the
    wait loop must pump the send comm too). TCP plane, 16 MB buffers."""
    n = 2
    rng = np.random.default_rng(7)
    xs = [rng.standard_normal(4 * 1024 * 1024).astype(np.float32)
          for _ in range(n)]
    res = _run_ring(TCPNet, n, lambda net, s, r, rank:
                    ring_allreduce_over_net(net, s, r, xs[rank], rank, n))
    want = np.sum(xs, axis=0)
    for r in range(n):
        np.testing.assert_allclose(res[r], want, rtol=1e-5, atol=1e-5)


@needs_native
@pytest.mark.parametrize("net_cls", PLANES)
@pytest.mark.parametrize("n", [2, 3, 4])
def test_alltoallv_over_net(net_cls, n):
    """Ragged counts, including empty segments."""
    from rocnrdma_tpu.transport.plugin import ring_alltoallv_over_net

    rng = np.random.default_rng(6)
    counts = rng.integers(0, 23, size=(n, n))
    counts[0, -1] = 0  # an empty lane
    segs = {r: [rng.standard_normal(counts[r, j]).astype(np.float32)
                for j in range(n)] for r in range(n)}
    res = _run_ring(net_cls, n, lambda net, s, r, rank:
                    ring_alltoallv_over_net(net, s, r, segs[rank], counts,
                                            rank, n))
    for r in range(n):
        for src in range(n):
            np.testing.assert_array_equal(res[r][src], segs[src][r])


@needs_native
def test_alltoallv_validates_counts():
    from rocnrdma_tpu.transport.plugin import ring_alltoallv_over_net

    def fn(net, s, r, rank):
        with pytest.raises(ValueError, match="elements"):
            ring_alltoallv_over_net(
                net, s, r, [np.zeros(3, np.float32)] * 2,
                np.array([[1, 2], [3, 4]]), rank, 2)
        return True

    assert all(_run_ring(TCPNet, 2, fn))


@needs_native
@pytest.mark.parametrize("net_cls", PLANES)
def test_sequential_collectives_share_comms(net_cls):
    """Back-to-back collectives on the same comms must not cross tags."""
    n = 3
    rng = np.random.default_rng(4)
    xs = [rng.standard_normal(500).astype(np.float32) for _ in range(n)]
    def fn(net, s, r, rank):
        first = ring_allreduce_over_net(net, s, r, xs[rank], rank, n)
        gathered = ring_allgather_over_net(net, s, r, xs[rank], rank, n)
        return first, gathered
    res = _run_ring(net_cls, n, fn)
    want_sum = np.sum(xs, axis=0)
    want_gather = np.stack(xs)
    for r in range(n):
        np.testing.assert_allclose(res[r][0], want_sum, rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(res[r][1], want_gather)


@needs_native
def test_alltoall_int_dtype_preserved():
    n = 2
    mats = [np.arange(n * 5, dtype=np.int64).reshape(n, 5) + 100 * r
            for r in range(n)]
    res = _run_ring(HostQPNet, n, lambda net, s, r, rank:
                    ring_alltoall_over_net(net, s, r, mats[rank], rank, n))
    for r in range(n):
        assert res[r].dtype == np.int64
        want = np.stack([mats[src][r] for src in range(n)])
        np.testing.assert_array_equal(res[r], want)


# ------------------------------------------------------------- ISSUE 12
# The self-tuning wire on the live ring: per-call model picks drive the
# streaming engine's frame/depth, the posting window generalizes past
# the fixed double buffer, and the negotiation gauge names the model
# version that chose — while results stay bitwise-correct.

from rocnrdma_tpu.metrics import WIRE
from rocnrdma_tpu.transport.tuner import HostWireModel, PlaneParams


def _allreduce_with_model(net_cls, n, elems, model_fn):
    rng = np.random.default_rng(7)
    xs = [rng.standard_normal(elems).astype(np.float32) for _ in range(n)]
    want = np.sum(xs, axis=0)

    def fn(net, s, r, rank):
        return ring_allreduce_over_net(net, s, r, xs[rank], rank, n)

    net = net_cls()
    net.wire_model = model_fn()  # per-test model: no process-wide state
    net.init()
    handles, listens = [], []
    for _ in range(n):
        h, l = net.listen()
        handles.append(h)
        listens.append(l)
    results: list = [None] * n
    errors: list = []

    def worker(rank):
        try:
            send_comm = net.connect(0, handles[(rank + 1) % n])
            recv_comm = net.accept(listens[rank])
            results[rank] = fn(net, send_comm, recv_comm, rank)
        except Exception as e:
            errors.append((rank, e))

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    assert not errors, errors
    net.close()
    for r in range(n):
        np.testing.assert_allclose(results[r], want, rtol=1e-3,
                                   atol=1e-5)
    return WIRE.negotiation()


@needs_native
def test_stream_runs_model_picked_frame_and_deep_window():
    # a pinned tiny frame + depth 4 posting window over a 4-rank ring
    # (6 hops): many frames per hop, receives posted 4 hops ahead —
    # the generalized window must deliver the exact allreduce
    neg = _allreduce_with_model(
        HostQPNet, 4, 16384,
        lambda: HostWireModel("shm", pin_frame=4096, pin_depth=4))
    assert neg["frame_bytes"] == 4096
    assert neg["pipeline_depth"] == 4
    assert neg["tuner_version"] == 0


@needs_native
def test_stream_depth_one_posting_window_still_correct():
    neg = _allreduce_with_model(
        HostQPNet, 3, 4096,
        lambda: HostWireModel("shm", pin_frame=2048, pin_depth=1))
    assert neg["pipeline_depth"] == 1


@needs_native
def test_stream_negotiation_carries_committed_version():
    def mk():
        m = HostWireModel("shm", pin_frame=8192, pin_depth=2)
        assert m.commit(PlaneParams(), 0, "test") == 1
        return m
    neg = _allreduce_with_model(HostQPNet, 2, 8192, mk)
    assert neg["tuner_version"] == 1


@needs_native
def test_disabled_model_keeps_the_legacy_static_wire():
    neg = _allreduce_with_model(
        HostQPNet, 2, 1 << 20,
        lambda: HostWireModel("shm", enabled=False))
    # the legacy pick: LG_CHUNK frames, double-buffered window
    assert neg["frame_bytes"] == HostQPNet.LG_CHUNK
    assert neg["pipeline_depth"] == 2


@needs_native
@pytest.mark.parametrize("net_cls", PLANES)
def test_model_picks_agree_across_ranks_on_ragged_verbs(net_cls):
    # the cross-rank frame-agreement property on the RAGGED verb whose
    # per-rank hop lists differ most: the pick key is max(counts), the
    # same value everywhere, so tags agree and the gather is exact
    n = 4
    counts = [1021, 7, 2048, 257]
    rng = np.random.default_rng(3)
    segs = [rng.standard_normal(c).astype(np.float32) for c in counts]

    def fn(net, s, r, rank):
        return ring_allgatherv_over_net(net, s, r, segs[rank], counts,
                                        rank, n)

    res = _run_ring(net_cls, n, fn)
    for r in range(n):
        for j in range(n):
            np.testing.assert_array_equal(res[r][j], segs[j])
