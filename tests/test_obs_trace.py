"""Causal collective tracing (rocnrdma_tpu.obs.trace): op-span
sampling, record building, cross-rank assembly + critical-path
attribution, replay digests, the flight-ring capacity guard, the
Perfetto critical-path lane, and THE acceptance run — a 4-rank shm
allreduce fleet with one rank's completions held by FaultNet, whose
critical path must name the delayed rank."""

import json
import re
import time

import pytest

from rocnrdma_tpu import native
from rocnrdma_tpu.obs import FLIGHT, FlightRecorder
from rocnrdma_tpu.obs import trace

needs_native = pytest.mark.skipif(
    not native.available(), reason="native library not buildable")


# ---------------------------------------------------------------------------
# op-span sampling + the span context
# ---------------------------------------------------------------------------


def _drive_op(rank, op=0, epoch=0, up=None, down=None, hold=0.0,
              frames=(1, 1)):
    """One synthetic traced op: stream-start, per-hop post/send/land
    events, a recv-wait of ``hold`` seconds."""
    with trace.op_span(epoch, 0, op, "ring_allreduce_over_net", rank):
        trace.record("stream-start", hops=len(frames), frame=64, depth=2,
                     up=up, down=down)
        for hop, n in enumerate(frames):
            for fi in range(n):
                trace.record("frame-posted", hop=hop, frame=fi, nbytes=64)
        trace.record("frame-sent", hop=0, frame=0)  # the opening burst
        for hop, n in enumerate(frames):
            if hold:
                time.sleep(hold)
                trace.record("recv-wait", hop=hop, frame=0, dur=hold)
            for fi in range(n):
                trace.record("frame-landed", tag=(hop << 16) | fi,
                             nbytes=64, dur=0.001)
            if hop + 1 < len(frames):  # forward: the next hop's send
                trace.record("frame-sent", hop=hop + 1, frame=0)


def test_sampling_every_nth_op(monkeypatch):
    monkeypatch.setenv("ROCNRDMA_TRACE_SAMPLE", "4")
    trace.TRACE.reset()
    for op in range(8):
        _drive_op(0, op=op)
    recs = trace.TRACE.snapshot()
    assert [r["op"] for r in recs] == [0, 4]


def test_sampling_zero_disables(monkeypatch):
    monkeypatch.setenv("ROCNRDMA_TRACE_SAMPLE", "0")
    trace.TRACE.reset()
    FLIGHT.reset()
    _drive_op(0, op=0)
    assert trace.TRACE.snapshot() == []
    assert not any(k.startswith("trace-op") for _, k, _ in FLIGHT.events())


def test_malformed_sample_env_degrades_to_default(monkeypatch):
    monkeypatch.setenv("ROCNRDMA_TRACE_SAMPLE", "every-other")
    assert trace.sample_every() == trace.DEFAULT_SAMPLE


def test_unsampled_op_stamps_nothing(monkeypatch):
    monkeypatch.setenv("ROCNRDMA_TRACE_SAMPLE", "2")
    FLIGHT.reset()
    trace.TRACE.reset()
    _drive_op(0, op=1)  # 1 % 2 != 0: unsampled
    assert trace.TRACE.snapshot() == []
    for _, kind, args in FLIGHT.events():
        assert "op" not in args, (kind, args)


def test_nested_span_stays_with_outer_op(monkeypatch):
    monkeypatch.setenv("ROCNRDMA_TRACE_SAMPLE", "1")
    trace.TRACE.reset()
    with trace.op_span(0, 0, 0, "outer", 0):
        with trace.op_span(0, 0, 4, "inner", 0):
            trace.record("frame-landed", tag=0, nbytes=8, dur=0.0)
    recs = trace.TRACE.snapshot()
    assert [r["verb"] for r in recs] == ["outer"]
    assert recs[0]["n_frames"] == 1  # the inner event landed in the outer op


def test_abort_closes_span_and_buffers_nothing(monkeypatch):
    """The span-pairing contract at runtime: an aborted attempt leaves
    a trace-op-abort on the timeline (analyzer pass #4f pins the static
    half), pushes NO record (partial frame counts are timing-shaped and
    would poison the replay digest), and clears the context."""
    monkeypatch.setenv("ROCNRDMA_TRACE_SAMPLE", "1")
    trace.TRACE.reset()
    FLIGHT.reset()
    with pytest.raises(TimeoutError):
        with trace.op_span(0, 0, 0, "ring_allreduce_over_net", 0):
            trace.record("frame-landed", tag=0, nbytes=8, dur=0.0)
            raise TimeoutError("peer died")
    kinds = [k for _, k, _ in FLIGHT.events()]
    assert "trace-op-start" in kinds and "trace-op-abort" in kinds
    assert "trace-op-end" not in kinds
    assert trace.TRACE.snapshot() == []
    assert not trace.tracing()


def test_suspended_block_is_not_billed_to_the_op(monkeypatch):
    """The p2p resume service's contract: work pumped from a traced
    op's progress hooks runs under trace.suspended(), so its waits are
    neither stamped with the op's identity nor billed to its buckets
    (the enclosing recv-wait already covers that wall time)."""
    monkeypatch.setenv("ROCNRDMA_TRACE_SAMPLE", "1")
    trace.TRACE.reset()
    FLIGHT.reset()
    with trace.op_span(0, 0, 0, "ring_allreduce_over_net", 0):
        with trace.suspended():
            assert not trace.tracing()
            trace.record("lane-admit-done", lane="bulk", dur=5.0)
        assert trace.tracing()
        trace.record("frame-landed", tag=0, nbytes=8, dur=0.0)
    (rec,) = trace.TRACE.snapshot()
    assert rec["waits"]["lane-admit"] == 0.0
    assert rec["n_frames"] == 1
    suspended_ev = [a for _, k, a in FLIGHT.events()
                    if k == "lane-admit-done"]
    assert suspended_ev and "op" not in suspended_ev[0]
    # outside any span, suspended() is a no-op
    with trace.suspended():
        assert not trace.tracing()


# ---------------------------------------------------------------------------
# record building + attribution
# ---------------------------------------------------------------------------


def test_record_buckets_sum_to_wall_span(monkeypatch):
    monkeypatch.setenv("ROCNRDMA_TRACE_SAMPLE", "1")
    trace.TRACE.reset()
    _drive_op(0, up=1, down=1, hold=0.01, frames=(1, 2))
    (rec,) = trace.TRACE.snapshot()
    assert rec["up"] == 1 and rec["down"] == 1
    assert rec["n_frames"] == 3
    assert [h[:2] for h in rec["hops"]] == [[0, 1], [1, 2]]
    att = trace.attribution(rec)
    assert set(att) == set(trace.BUCKETS)
    # the residual definition makes the sum EXACT by construction
    assert sum(att.values()) == pytest.approx(rec["wall_s"], abs=1e-12)
    assert att["recv-wait"] == pytest.approx(0.02, rel=0.5)


def test_trace_buffer_is_bounded():
    buf = trace.TraceBuffer(capacity=3)
    for i in range(7):
        buf.push({"op": i})
    assert [r["op"] for r in buf.snapshot()] == [4, 5, 6]


# ---------------------------------------------------------------------------
# cross-rank assembly: critical path, hold/xfer blame, scoreboard
# ---------------------------------------------------------------------------


def _rec(rank, up, down, hops, t_start=0.0, wall=None, waits=None):
    """hops: list of (hop, frames, post, land, sent)."""
    wall = wall if wall is not None else max(h[3] for h in hops) - t_start
    w = {b: 0.0 for b in trace.WAIT_BUCKETS}
    w.update(waits or {})
    return {"v": 1, "epoch": 0, "chan": 0, "op": 0,
            "verb": "ring_allreduce_over_net", "rank": rank, "up": up,
            "down": down, "t_start": t_start, "wall_s": wall,
            "n_frames": sum(h[1] for h in hops),
            "hops": [list(h) for h in hops], "waits": w}


def _two_rank_records(hold_on=1):
    """A 2-rank, 2-hop ring where one rank sits on its frames for
    100 ms before forwarding (sender-side hold)."""
    d = 0.1 if hold_on == 1 else 0.0
    e = 0.1 - d
    # rank 0: lands hop 0 at 0.01, forwards hop 1 after its own hold e
    r0 = _rec(0, up=1, down=1, t_start=0.0, hops=[
        (0, 1, 0.001, 0.010, 0.002),        # recv hop 0; sent hop-0 @2ms
        (1, 1, 0.001, 0.010 + d + 0.005, 0.010 + e)])
    # rank 1: lands hop 0 from rank 0, holds d, forwards hop 1
    r1 = _rec(1, up=0, down=0, t_start=0.0, hops=[
        (0, 1, 0.001, 0.012, 0.001),
        (1, 1, 0.001, 0.02, 0.012 + d)])
    return [r0, r1]


def test_critical_path_blames_the_holding_rank():
    trees = trace.assemble(_two_rank_records(hold_on=1), world=2)
    assert len(trees) == 1
    t = trees[0]
    assert t["critical_path"], t
    # rank 1 held hop 1's frame 100 ms before forwarding: the hold
    # lands on rank 1's share and the scoreboard names it
    assert t["cp_rank"] == 1
    assert t["cp_share"]["1"] > 10 * t["cp_share"]["0"]
    sb = trace.scoreboard(trees)
    assert sb["straggler"] == 1
    assert sb["share"]["1"] > 0.9
    assert sb["worst_hop"].get("1")


def test_critical_path_xfer_blames_the_receiving_rank():
    """A prompt forward whose LANDING is late (a held completion
    report, a slow fold) blames the RECEIVER — the hold/xfer split."""
    r0 = _rec(0, up=1, down=1, t_start=0.0, hops=[
        (0, 1, 0.001, 0.010, 0.002),
        (1, 1, 0.001, 0.120, 0.011)])   # rank 1 forwarded at 12ms...
    r1 = _rec(1, up=0, down=0, t_start=0.0, hops=[
        (0, 1, 0.001, 0.011, 0.001),
        (1, 1, 0.001, 0.02, 0.012)])
    trees = trace.assemble([r0, r1], world=2)
    t = trees[0]
    # ...but rank 0's landing came 108ms later: blame rank 0 (receiver)
    assert t["cp_rank"] == 0
    assert t["worst_hop"]["blame"] == 0


def test_assemble_skips_partial_ops_when_world_known():
    recs = _two_rank_records()
    assert trace.assemble(recs[:1], world=2) == []
    assert len(trace.assemble(recs[:1])) == 1  # worldless: best effort


def test_assemble_groups_by_epoch_chan_op():
    recs = _two_rank_records()
    moved = [dict(r, epoch=1) for r in recs]
    trees = trace.assemble(recs + moved, world=2)
    assert [(t["epoch"], t["op"]) for t in trees] == [(0, 0), (1, 0)]


# ---------------------------------------------------------------------------
# replay digest: structural only
# ---------------------------------------------------------------------------


def test_digest_excludes_wall_clock_fields():
    a = _two_rank_records()
    b = []
    for r in _two_rank_records():
        r = dict(r, t_start=r["t_start"] + 5.0,
                 wall_s=r["wall_s"] * 3,
                 waits={k: v + 1.0 for k, v in r["waits"].items()},
                 hops=[[h[0], h[1], h[2] + 9, h[3] + 9, h[4] + 9]
                       for h in r["hops"]])
        b.append(r)
    assert trace.digest(a) == trace.digest(b)
    # a STRUCTURAL change (frame count) changes the digest
    c = [dict(r) for r in _two_rank_records()]
    c[0] = dict(c[0], hops=[[0, 2, 0.001, 0.01, 0.002]]
                + [list(h) for h in c[0]["hops"][1:]])
    assert trace.digest(c) != trace.digest(a)
    # ...and the digest is order-independent (records arrive per rank)
    assert trace.digest(list(reversed(a))) == trace.digest(a)


def test_records_from_events_round_trip(monkeypatch):
    """The Perfetto merger's path: records rebuilt from a dump's
    op-stamped events match the live collector's records (same builder
    underneath), and aborted spans are skipped."""
    monkeypatch.setenv("ROCNRDMA_TRACE_SAMPLE", "1")
    trace.TRACE.reset()
    FLIGHT.reset()
    _drive_op(3, op=0, up=2, down=0, frames=(1, 1))
    with pytest.raises(RuntimeError):
        with trace.op_span(0, 0, 1, "ring_allreduce_over_net", 3):
            trace.record("frame-landed", tag=0, nbytes=8, dur=0.0)
            raise RuntimeError("aborted attempt")
    (live,) = trace.TRACE.snapshot()
    rebuilt = trace.records_from_events(FLIGHT.events(), rank=3,
                                        sync_ts=FLIGHT.sync_ts)
    assert len(rebuilt) == 1  # the aborted span yields NO record
    r = rebuilt[0]
    assert (r["epoch"], r["chan"], r["op"]) == (0, 0, 0)
    assert r["up"] == 2 and r["n_frames"] == live["n_frames"]
    assert [h[:2] for h in r["hops"]] == [h[:2] for h in live["hops"]]
    assert trace.digest([r]) == trace.digest([live])


# ---------------------------------------------------------------------------
# the flight-ring capacity guard (satellite): saturation is recorded
# ---------------------------------------------------------------------------


def test_flight_ring_saturation_recorded_once():
    rec = FlightRecorder(capacity=4)
    for i in range(4):
        rec.record("tick", i=i)
    assert rec.saturated is False
    rec.record("tick", i=4)  # first eviction
    assert rec.saturated is True
    kinds = [k for _, k, _ in rec.events()]
    assert kinds.count("flight-ring-saturated") == 1
    # the marker is meta: the lifetime count stays the REAL event count
    assert rec.recorded() == 5
    for i in range(10):
        rec.record("tick", i=5 + i)
    # one marker ever; reset re-arms
    assert rec.saturated is True
    rec.reset()
    assert rec.saturated is False


def test_format_trace_renders():
    trees = trace.assemble(_two_rank_records(), world=2)
    text = trace.format_trace({"epoch": 0, "sample": 4, "ops": trees,
                               "scoreboard": trace.scoreboard(trees)})
    assert "ring_allreduce_over_net" in text
    assert "cp-rank 1" in text
    assert "straggler rank 1" in text
    for bucket in trace.BUCKETS:
        assert bucket in text


# ---------------------------------------------------------------------------
# THE acceptance run: 4 ranks, one delayed, cross-process
# ---------------------------------------------------------------------------


def _trace_lines(result):
    m = re.search(r"^TRACE (\[.*\])$", result.stdout, re.M)
    assert m, f"rank {result.process_id} printed no TRACE line:\n" \
              f"{result.stdout}\n{result.stderr}"
    return json.loads(m.group(1))


def _tracelog(result):
    m = re.search(r"^TRACELOG ([0-9a-f]{64})$", result.stdout, re.M)
    assert m, f"rank {result.process_id} printed no TRACELOG line"
    return m.group(1)


@pytest.mark.chaos
@needs_native
def test_delayed_rank_owns_the_critical_path(monkeypatch):
    """ISSUE 10 acceptance: a 4-rank shm allreduce fleet where ONLY
    rank 3's receive completions are held (FaultNet ``test_delay``)
    must assemble into critical paths naming rank 3 on every sampled
    op, with each rank's attribution buckets summing to its op wall
    span — and two same-seed runs must print identical structural
    trace digests on every rank."""
    from rocnrdma_tpu.runtime.multiprocess import run_workers

    monkeypatch.setenv("ROCNRDMA_TRACE_SAMPLE", "1")
    n, seed, rounds, victim = 4, 7, 3, 3
    runs = [run_workers(n, "trace-delay", timeout_s=150.0,
                        fault_rank=victim, seed=seed, rounds=rounds,
                        size=2048) for _ in range(2)]
    for results in runs:
        for r in results:
            assert r.returncode == 0, \
                f"rank {r.process_id} exited {r.returncode}:\n" \
                f"{r.stdout}\n{r.stderr}"

    records = [rec for r in runs[0] for rec in _trace_lines(r)]
    trees = trace.assemble(records, world=n)
    assert len(trees) == rounds  # sample=1: every op assembled
    blamed = 0
    for t in trees:
        assert t["critical_path"], t
        blamed += t["cp_rank"] == victim
        for rank_s, info in t["ranks"].items():
            got = sum(info["attribution"].values())
            assert got == pytest.approx(info["wall_s"], abs=1e-9), \
                (rank_s, info)
    # the delayed rank owns the critical path of (nearly) every op:
    # one noisy op is allowed — an oversubscribed box can hand one
    # round's longest wall to a GIL-starved healthy rank — because the
    # CONSUMER of these verdicts (the ISSUE-16 evasion engine) scores
    # the windowed scoreboard below, never a single op
    assert blamed >= rounds - 1, [t["cp_share"] for t in trees]
    sb = trace.scoreboard(trees)
    assert sb["straggler"] == victim
    assert sb["share"][str(victim)] > 0.5

    # replay equality: the structural digest is a pure function of the
    # seed — identical per rank across the two runs
    first = [_tracelog(r) for r in runs[0]]
    second = [_tracelog(r) for r in runs[1]]
    assert first == second
    # and not vacuously so: every rank recorded ops
    assert all(_trace_lines(r) for r in runs[0])


@pytest.mark.chaos
@needs_native
def test_chrome_merge_renders_critical_path_lane(tmp_path, monkeypatch):
    """The Perfetto acceptance: the merged trace carries the
    critical-path lane, and every cp-hop slice's end coincides with a
    frame slice of the same rank — both lanes are derived from the
    same events, so they align 1:1."""
    from rocnrdma_tpu.bench import bench_host
    from rocnrdma_tpu.obs import chrome

    monkeypatch.setenv("ROCNRDMA_TRACE_SAMPLE", "1")
    monkeypatch.setenv("ROCNRDMA_FLIGHT_DUMP", str(tmp_path))
    rc = bench_host.main(["--ranks", "2", "--plane", "shm", "--sizes",
                          "64K", "--collectives", "allreduce",
                          "--repeats", "2", "--iters", "2"])
    assert rc == 0
    merged = chrome.merge([str(tmp_path / f"flight_rank{r}.json")
                           for r in (0, 1)])
    names = {(e["pid"], e.get("args", {}).get("name"))
             for e in merged["traceEvents"] if e.get("ph") == "M"}
    assert (0, "critical-path") in names and (1, "critical-path") in names
    total = 0
    for r in (0, 1):
        cps = chrome.critical_path_slices(merged, r)
        frames = chrome.frame_slices(merged, r)
        frame_ends = [f["ts"] + f["dur"] for f in frames]
        for c in cps:
            end = c["ts"] + c["dur"]
            assert any(abs(fe - end) < 1.0 for fe in frame_ends), \
                (r, c, frame_ends)
            assert c["dur"] >= 0 and c["ts"] >= 0
            assert {"epoch", "chan", "op", "hop", "src"} \
                <= set(c["args"])
        total += len(cps)
    assert total > 0, "no critical-path slices in the merged trace"
    # the per-op span markers ride the same lane
    assert any(e.get("name") == "trace-op-end"
               for e in merged["traceEvents"])


@needs_native
def test_trace_stats_assembles_across_ranks(monkeypatch):
    """ProcessGroup.trace_stats(): both ranks' sampled op records (the
    local buffer plus the peer's published fleet snapshot) assemble
    into trees with critical paths and the scoreboard."""
    import threading

    import numpy as np

    from rocnrdma_tpu import distributed as dist
    from rocnrdma_tpu.transport import bootstrap

    monkeypatch.setenv("ROCNRDMA_TRACE_SAMPLE", "1")
    trace.TRACE.reset()
    n = 2
    store = bootstrap.BootstrapServer(n_ranks=n)
    out, errors = [None] * n, []
    barrier = threading.Barrier(n)

    def worker(rank):
        pg = None
        try:
            pg = dist.init_process_group(
                rank=rank, world_size=n, store_handle=store.handle,
                plane="shm", group_name="obs-trace")
            for _ in range(2):
                pg.all_reduce(np.arange(4096, dtype=np.float32))
            pg.publish_telemetry()
            barrier.wait(timeout=30)
            if rank == 0:
                out[0] = pg.trace_stats()
        except Exception as e:  # pragma: no cover - surfaced via assert
            errors.append((rank, repr(e)))
        finally:
            if pg is not None:
                pg.destroy()

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    store.close()
    assert not errors, errors
    stats = out[0]
    assert stats["sample"] == 1
    assert stats["ops"], stats
    for t in stats["ops"]:
        assert t["critical_path"]
        assert set(t["ranks"]) == {"0", "1"}
    assert stats["scoreboard"]["ops"] == len(stats["ops"])


def test_trace_cli_reads_store_and_renders(capsys, monkeypatch):
    """The observer CLI: assembles the published records from the
    store (one-shot and --json) without being a member."""
    from rocnrdma_tpu.obs import fleet
    from rocnrdma_tpu.transport import bootstrap

    monkeypatch.setenv("ROCNRDMA_TRACE_SAMPLE", "1")
    server = bootstrap.BootstrapServer(n_ranks=1)
    client = bootstrap.BootstrapClient(server.handle, 0, timeout_s=5.0)
    try:
        # publish two ranks' snapshots by hand (the agent's shape),
        # each carrying one rank's half of a 2-rank traced op
        recs = {r["rank"]: r for r in _two_rank_records()}
        for orig in (0, 1):
            snap = {"v": 1, "rank": orig, "orig": orig, "epoch": 0,
                    "seq": 1, "plane": "shm", "health": "ok",
                    "transitions": [], "heals": 0, "window_s": 1.0,
                    "wire": {}, "wire_delta": {}, "verb_latency": {},
                    "flight": {"recorded": 0, "capacity": 4096},
                    "trace": [recs[orig]]}
            client.set(fleet.snapshot_key("tg", 0, orig),
                       json.dumps(snap), timeout_s=5.0)
        client.set(fleet.meta_key("tg"),
                   json.dumps({"epoch": 0, "members": [0, 1],
                               "world": 2, "group": "tg"}),
                   timeout_s=5.0)
        rc = trace.main(["--store", server.handle, "--group", "tg"])
        assert rc == 0
        text = capsys.readouterr().out
        assert "straggler rank 1" in text
        rc = trace.main(["--store", server.handle, "--group", "tg",
                         "--json"])
        assert rc == 0
        snap = json.loads(capsys.readouterr().out)
        assert snap["ops"][0]["cp_rank"] == 1
        assert snap["scoreboard"]["straggler"] == 1
    finally:
        client.close()
        server.close()


def test_trace_cli_names_missing_telemetry(capsys):
    from rocnrdma_tpu.transport import bootstrap

    server = bootstrap.BootstrapServer(n_ranks=1)
    try:
        rc = trace.main(["--store", server.handle, "--group", "ghost"])
    finally:
        server.close()
    assert rc == 1
    assert "no fleet telemetry" in capsys.readouterr().err
