"""Chunk-pipelined double binary tree (collectives/ptree.py) — the
streaming tree VERDICT r2 item 1 demanded (SURVEY §7's hard part)."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from rocnrdma_tpu import runtime as rt
from rocnrdma_tpu.collectives import ptree_allreduce
from rocnrdma_tpu.collectives.schedule import (
    dbtree_depths,
    dbtree_parents,
    ptree_ticks,
    sim_ptree_allreduce,
)
from rocnrdma_tpu.transport import Transport

RANK = rt.mesh.RANK_AXIS


def _run(n, op="sum", size=97, chunks=4, dtype=np.float32):
    rng = np.random.default_rng(n * 17 + chunks)
    x = rng.standard_normal((n, size)).astype(dtype)
    mesh = rt.rank_mesh(n)
    f = jax.jit(jax.shard_map(
        lambda s: ptree_allreduce(s[0], RANK, op=op, chunks=chunks)[None],
        mesh=mesh, in_specs=(P(RANK),), out_specs=P(RANK), check_vma=False))
    return x, np.asarray(f(x))


@pytest.mark.parametrize("n", [2, 3, 4, 5, 6, 7, 8])
def test_ptree_matches_numpy(devices, n):
    x, out = _run(n)
    np.testing.assert_allclose(out, np.broadcast_to(x.sum(0), out.shape),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("chunks", [1, 2, 3, 8])
def test_ptree_chunk_counts(devices, chunks):
    # C=1 degenerates to the level-synchronous tree; any C computes the
    # same reduction
    x, out = _run(8, chunks=chunks)
    np.testing.assert_allclose(out, np.broadcast_to(x.sum(0), out.shape),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("op,npf", [("max", np.max), ("min", np.min),
                                    ("avg", np.mean), ("prod", np.prod)])
def test_ptree_ops(devices, op, npf):
    x, out = _run(6, op=op, size=33)
    np.testing.assert_allclose(out, np.broadcast_to(npf(x, axis=0), out.shape),
                               rtol=1e-4, atol=1e-5)


def test_ptree_ragged_size(devices):
    # size neither divisible by 2 halves nor by C chunks: padding must not
    # leak
    x, out = _run(5, size=41, chunks=3)
    np.testing.assert_allclose(out, np.broadcast_to(x.sum(0), out.shape),
                               rtol=1e-4, atol=1e-5)


def test_ptree_bad_chunks(devices):
    with pytest.raises(ValueError, match="chunks >= 1"):
        _run(4, chunks=0)


def test_ptree_bf16(devices):
    import jax.numpy as jnp
    rng = np.random.default_rng(7)
    x = rng.standard_normal((8, 64)).astype(np.float32)
    mesh = rt.rank_mesh(8)
    f = jax.jit(jax.shard_map(
        lambda s: ptree_allreduce(s[0], RANK)[None],
        mesh=mesh, in_specs=(P(RANK),), out_specs=P(RANK), check_vma=False))
    out = np.asarray(f(jnp.asarray(x, jnp.bfloat16)).astype(jnp.float32))
    np.testing.assert_allclose(out, np.broadcast_to(x.sum(0), out.shape),
                               rtol=5e-2, atol=5e-2)


@pytest.mark.parametrize("n", [2, 5, 8, 15, 64])
@pytest.mark.parametrize("chunks", [1, 4, 7])
def test_ptree_sim_oracle(n, chunks):
    # the pure-numpy walker over the same tick tables (no devices) —
    # contract-scale 64 ranks included
    rng = np.random.default_rng(n + chunks)
    bufs = rng.standard_normal((n, 50)).astype(np.float32)
    out = sim_ptree_allreduce(bufs, chunks=chunks)
    want = np.broadcast_to(bufs.sum(0), out.shape)
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("n", [8, 64])
def test_ptree_tick_structure(n):
    # pipeline laws: per phase C+D-1 ticks; every tree edge carries every
    # chunk exactly once per phase; within a substep, destinations are
    # unique (a valid ppermute) and all of a parent's arrivals in one tick
    # share a chunk index (the 3-operand fold's precondition)
    C = 5
    for parents in dbtree_parents(n):
        depths = dbtree_depths(parents)
        up, down = ptree_ticks(parents, C)
        assert len(up) == C + max(depths) - 1
        assert len(down) == C + max(depths) - 1
        edges_up = sorted((c, p, i) for tick in up for sub in tick
                          for c, p, i in sub)
        want = sorted((c, parents[c], i) for c in range(n)
                      if parents[c] != -1 for i in range(C))
        assert edges_up == want
        edges_down = sorted((c, p, i) for tick in down for sub in tick
                            for p, c, i in sub)
        assert edges_down == want
        for tick in up:
            for sub in tick:
                dsts = [p for _, p, _ in sub]
                assert len(dsts) == len(set(dsts))
            by_parent = {}
            for sub in tick:
                for c, p, i in sub:
                    by_parent.setdefault(p, set()).add(i)
            assert all(len(v) == 1 for v in by_parent.values())


def test_ptree_streaming_not_level_synchronous():
    # the pipelining claim itself: with C > 1, some tick carries chunks of
    # DIFFERENT indices at different depths simultaneously (level t of
    # chunk i overlapping level t-1 of chunk i+1) — the property the
    # level-synchronous dtree lacks
    parents = dbtree_parents(16)[0]
    up, _ = ptree_ticks(parents, 4)
    assert any(len({i for sub in tick for _, _, i in sub}) > 1
               for tick in up)


def test_ptree_via_transport_and_group(devices):
    t = Transport(rt.rank_mesh(8))
    x = t.shard(np.random.default_rng(3)
                .standard_normal((8, 64)).astype(np.float32))
    out = np.asarray(t.allreduce(x, "ptree"))
    np.testing.assert_allclose(
        out, np.broadcast_to(np.asarray(x).sum(0), out.shape),
        rtol=1e-5, atol=1e-5)
    assert any(k.startswith("allreduce/ptree") for k in t.stats())


def test_ptree_rejects_2d_mesh(devices):
    t = Transport(rt.slice_mesh(2, 4))
    x = t.shard(np.zeros((2, 4, 8), np.float32))
    with pytest.raises(ValueError, match="no 'ptree' schedule on a 2-D"):
        t.allreduce(x, "ptree")
