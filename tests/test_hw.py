"""The shared chip-constants table (rocnrdma_tpu/hw.py) — the one source
bench.py's roofline, the tuner's calibration, and the MFU peak all read."""

import pytest

from rocnrdma_tpu import hw


def test_match_rule_first_substring_wins():
    # "TPU v5 lite" must hit "v5 lite" (819 GB/s HBM), NOT the "v5"
    # entry that describes v5p-class chips — dict order is load-bearing
    assert hw.chip_for("TPU v5 lite").hbm_GBps == 819.0
    assert hw.chip_for("TPU v6 lite").hbm_GBps == 1638.0
    assert hw.chip_for("TPU v5p").hbm_GBps == 2765.0
    assert hw.chip_for("TPU v5").hbm_GBps == 2765.0
    assert hw.chip_for("TPU v4").hbm_GBps == 1228.0


def test_unknown_and_empty_kinds():
    assert hw.chip_for("warp drive") is None
    assert hw.chip_for("") is None
    assert hw.chip_for(None) is None


def test_per_link_rates_and_peaks_sane():
    for kind, chip in hw.CHIPS.items():
        assert chip.ici_links > 0
        per_link = chip.ici_GBps / chip.ici_links
        # per-link ICI is always well under HBM; peaks are positive
        assert 0 < per_link < chip.hbm_GBps
        assert chip.bf16_tflops > 0


def test_measured_fraction_is_a_fraction():
    assert 0.5 < hw.MEASURED_HBM_FRAC < 1.0


def test_bench_roofline_consumes_the_table():
    # bench.py's _roofline must actually read THIS table (a private copy
    # of the constants would silently desync calibration from scoring)
    import importlib.util
    import os
    import types

    spec = importlib.util.spec_from_file_location(
        "bench_script_hw", os.path.join(os.path.dirname(__file__), "..",
                                        "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    dev = types.SimpleNamespace(device_kind="TPU v5 lite")
    chip = hw.chip_for(dev.device_kind)
    assert bench._roofline(dev) == (chip.hbm_GBps, chip.ici_GBps)
    # unknown kind: the CPU fallback, never a crash
    assert bench._roofline(types.SimpleNamespace(device_kind="mystery")) \
        == bench._CPU_FALLBACK


# ------------------------------------------- r5: per-chip calibration overrides

def test_calibration_override_precedence(tmp_path, monkeypatch):
    # VERDICT r4 missing #3: a persisted hw_<kind>.json must override the
    # v5e defaults for exactly the fields it carries, and fall through for
    # the rest; deleting it restores the defaults
    monkeypatch.setenv("RNR_HW_CAL_DIR", str(tmp_path))
    monkeypatch.delenv("RNR_HW_CAL", raising=False)
    kind = "TPU v9 imaginary"
    assert hw.fold_ladder_for(kind) == hw.MEASURED_FOLD_LADDER
    assert hw.dispatch_alpha_s(kind) == hw.MEASURED_DISPATCH_ALPHA_S
    assert hw.hbm_frac(kind) == hw.MEASURED_HBM_FRAC
    path = hw.save_calibration(kind, {
        "fold_ladder": {"2": 100.0, "8": 400.0},
        "dispatch_alpha_s": 5e-8})
    assert path.startswith(str(tmp_path))
    assert hw.fold_ladder_for(kind) == {2: 100.0, 8: 400.0}
    assert hw.dispatch_alpha_s(kind) == 5e-8
    # hbm_frac absent from the artifact -> default falls through
    assert hw.hbm_frac(kind) == hw.MEASURED_HBM_FRAC
    # the override ladder drives fold_rate_scale: 8-op folds 4x the
    # pairwise rate here (vs ~1.11x on the v5e default)
    assert hw.fold_rate_scale(8, kind) == 0.25
    assert hw.fold_rate_scale(8) != 0.25
    import os
    os.unlink(path)
    hw._CAL_CACHE.clear()
    assert hw.fold_ladder_for(kind) == hw.MEASURED_FOLD_LADDER


def test_calibration_rejects_malformed_artifacts(tmp_path, monkeypatch):
    # a torn/garbage file must behave as absent, never crash the fleet;
    # a ladder missing the pairwise anchor is unusable and ignored
    monkeypatch.setenv("RNR_HW_CAL_DIR", str(tmp_path))
    monkeypatch.delenv("RNR_HW_CAL", raising=False)
    kind = "TPU v9 torn"
    p = hw.calibration_path(kind)
    with open(p, "w") as fp:
        fp.write("{not json")
    hw._CAL_CACHE.clear()
    assert hw.fold_ladder_for(kind) == hw.MEASURED_FOLD_LADDER
    hw.save_calibration(kind, {"fold_ladder": {"8": 400.0}})  # no anchor
    assert hw.fold_ladder_for(kind) == hw.MEASURED_FOLD_LADDER


def test_calibration_flows_into_tuner_constants(tmp_path, monkeypatch):
    # constants_for and the khd radix pick must consult the override: a
    # chip whose measured ladder STOPS paying past 8-wide folds must not
    # get the v5e (64,) pick at the contract point
    monkeypatch.setenv("RNR_HW_CAL_DIR", str(tmp_path))
    monkeypatch.delenv("RNR_HW_CAL", raising=False)
    from rocnrdma_tpu.transport.tuner import constants_for, khd_model_digits
    kind = "TPU v5p"
    a, b, hb = constants_for(kind, "allreduce")
    assert khd_model_digits("allreduce", 64, 1 << 30, a, b, hb,
                            device_kind=kind) == (64,)
    hw.save_calibration(kind, {
        # narrow folds fast, wide folds collapse: the pick must retreat
        "fold_ladder": {"2": 660.0, "8": 740.0, "16": 740.0, "32": 300.0,
                        "64": 200.0},
        "dispatch_alpha_s": 4.0e-8})
    a2, b2, hb2 = constants_for(kind, "allreduce")
    assert a2 == hw.ICI_HOP_S + 4.0e-8
    pick = khd_model_digits("allreduce", 64, 1 << 30, a2, b2, hb2,
                            device_kind=kind)
    assert max(pick) <= 16, pick
    hw._CAL_CACHE.clear()
