"""The shared chip-constants table (rocnrdma_tpu/hw.py) — the one source
bench.py's roofline, the tuner's calibration, and the MFU peak all read."""

import pytest

from rocnrdma_tpu import hw


def test_match_rule_first_substring_wins():
    # "TPU v5 lite" must hit "v5 lite" (819 GB/s HBM), NOT the "v5"
    # entry that describes v5p-class chips — dict order is load-bearing
    assert hw.chip_for("TPU v5 lite").hbm_GBps == 819.0
    assert hw.chip_for("TPU v6 lite").hbm_GBps == 1638.0
    assert hw.chip_for("TPU v5p").hbm_GBps == 2765.0
    assert hw.chip_for("TPU v5").hbm_GBps == 2765.0
    assert hw.chip_for("TPU v4").hbm_GBps == 1228.0


def test_unknown_and_empty_kinds():
    assert hw.chip_for("warp drive") is None
    assert hw.chip_for("") is None
    assert hw.chip_for(None) is None


def test_per_link_rates_and_peaks_sane():
    for kind, chip in hw.CHIPS.items():
        assert chip.ici_links > 0
        per_link = chip.ici_GBps / chip.ici_links
        # per-link ICI is always well under HBM; peaks are positive
        assert 0 < per_link < chip.hbm_GBps
        assert chip.bf16_tflops > 0


def test_measured_fraction_is_a_fraction():
    assert 0.5 < hw.MEASURED_HBM_FRAC < 1.0


@pytest.mark.parametrize("kind,expect_guard", [("TPU v5 lite", True),
                                               ("mystery-chip", False)])
def test_bench_roofline_consumes_the_table(kind, expect_guard):
    # bench.py's _roofline and guard logic key off chip_for — the same
    # dict; a kind missing from CHIPS must fall back, never crash
    chip = hw.chip_for(kind)
    assert (chip is not None) == expect_guard
