"""The shared chip-constants table (rocnrdma_tpu/hw.py) — the one source
bench.py's roofline, the tuner's calibration, and the MFU peak all read."""

import pytest

from rocnrdma_tpu import hw


def test_match_rule_first_substring_wins():
    # "TPU v5 lite" must hit "v5 lite" (819 GB/s HBM), NOT the "v5"
    # entry that describes v5p-class chips — dict order is load-bearing
    assert hw.chip_for("TPU v5 lite").hbm_GBps == 819.0
    assert hw.chip_for("TPU v6 lite").hbm_GBps == 1638.0
    assert hw.chip_for("TPU v5p").hbm_GBps == 2765.0
    assert hw.chip_for("TPU v5").hbm_GBps == 2765.0
    assert hw.chip_for("TPU v4").hbm_GBps == 1228.0


def test_unknown_and_empty_kinds():
    assert hw.chip_for("warp drive") is None
    assert hw.chip_for("") is None
    assert hw.chip_for(None) is None


def test_per_link_rates_and_peaks_sane():
    for kind, chip in hw.CHIPS.items():
        assert chip.ici_links > 0
        per_link = chip.ici_GBps / chip.ici_links
        # per-link ICI is always well under HBM; peaks are positive
        assert 0 < per_link < chip.hbm_GBps
        assert chip.bf16_tflops > 0


def test_measured_fraction_is_a_fraction():
    assert 0.5 < hw.MEASURED_HBM_FRAC < 1.0


def test_bench_roofline_consumes_the_table():
    # bench.py's _roofline must actually read THIS table (a private copy
    # of the constants would silently desync calibration from scoring)
    import importlib.util
    import os
    import types

    spec = importlib.util.spec_from_file_location(
        "bench_script_hw", os.path.join(os.path.dirname(__file__), "..",
                                        "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    dev = types.SimpleNamespace(device_kind="TPU v5 lite")
    chip = hw.chip_for(dev.device_kind)
    assert bench._roofline(dev) == (chip.hbm_GBps, chip.ici_GBps)
    # unknown kind: the CPU fallback, never a crash
    assert bench._roofline(types.SimpleNamespace(device_kind="mystery")) \
        == bench._CPU_FALLBACK
